"""Hand-written Pallas TPU kernels for the hot ops.

The reference's analogue layer is the cuDNN-backed operator variants
(``src/operator/cudnn_*``, selected at CreateOp when available) and NVRTC
runtime kernels (``src/common/mxrtc.cc``). Here the default path is XLA
fusion; these kernels cover what XLA does not fuse well:

* ``flash_attention`` — streaming-softmax attention tiled for VMEM: one
  pass over K/V blocks per query block, f32 accumulators, MXU matmuls.
  O(T) memory instead of O(T²), forward AND backward: the forward also
  emits the per-row logsumexp, and the ``jax.custom_vjp`` backward is a
  pair of Pallas kernels (dQ tiled over query blocks, dK/dV over key
  blocks) that stream-recompute the probability blocks from (q, k, lse)
  instead of materializing the T×T matrix — training memory through the
  attention op is linear in sequence length.
* ``fused_linear`` — matmul + bias + activation epilogue in one kernel
  (the reference fuses this per-op in mshadow: fully_connected-inl.h).

Kernels run on TPU; on CPU (tests) they run under the Pallas interpreter,
keeping the backend-consistency oracle (SURVEY.md §4.3) meaningful.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "fused_linear", "striped_pair_attention",
           "matmul_stats", "paged_attention", "default_paged_block_k",
           "quant_matmul", "fused_decode_attention", "dispatch_count",
           "reset_dispatch_count"]


def _use_interpret():
    return jax.default_backend() != "tpu"


# Trace-time kernel-dispatch accounting: every public kernel entry
# bumps this when it STAGES a pallas_call (i.e. once per appearance in
# a traced program — each appearance is one device dispatch per
# execution of that program). bench.py's serving probes read it around
# a decode-program trace to report dispatches-per-round, the headline
# the fused decode chain exists to cut (HLO-level counting cannot see
# kernels under the CPU interpreter, which inlines them).
_DISPATCHES = 0


def _count_dispatch(n=1):
    global _DISPATCHES
    _DISPATCHES += n


def dispatch_count():
    """Pallas kernel dispatches staged since the last
    :func:`reset_dispatch_count` (trace-time count; see above)."""
    return _DISPATCHES


def reset_dispatch_count():
    global _DISPATCHES
    _DISPATCHES = 0


def _round_up(x, m):
    return (x + m - 1) // m * m


def default_attn_blocks(head_dim):
    """(block_q, block_k) default for the flash/ring kernels: 512
    tiles measured -33% on the 124M-LM step for head_dim <= 128
    (doc/performance.md round 4); large head dims overflow VMEM at 512.
    MXNET_FLASH_BLOCK_Q/K override.

    Known single-chip ceiling: the BACKWARD kernels keep full-sequence
    q/do/lse/dcap rows in VMEM (the [T, 1] residuals tile to 128
    lanes), which at T=8192 exceeds scoped VMEM at >=256 blocks — and
    this environment's compile relay crashes outright at 128. Full
    (non-windowed) attention trains longer sequences via sp/ring
    sharding (SequenceParallelTrainer) where each shard's local T
    stays below the limit; the ring impls do not support window>0, so
    windowed training is bounded by this ceiling."""
    import os
    d = 512 if head_dim <= 128 else 128
    return (int(os.environ.get("MXNET_FLASH_BLOCK_Q", d)),
            int(os.environ.get("MXNET_FLASH_BLOCK_K", d)))


# ---------------------------------------------------------------------------
# flash attention

def _window_lo(qi, block_q, block_k, window):
    """First key block any row of query block ``qi`` can see under a
    sliding window: max(0, (qi*block_q - (window-1)) // block_k), in
    the kernels' int32 arithmetic (shared by the fwd and dQ kernels so
    their skip bounds cannot drift apart)."""
    return jnp.maximum(jnp.int32(0),
                       lax.div(qi * jnp.int32(block_q)
                               - jnp.int32(window - 1),
                               jnp.int32(block_k)))


def _attn_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q,
                     block_k, seq_k, causal, scale, window=0):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [block_q, D]
    bq, d = q.shape
    # plain python int: pl.cdiv yields a numpy int64 scalar, which would
    # type the fori_loop counter as i64 — Mosaic cannot lower i64 and its
    # int64->int32 conversion helper recurses infinitely
    nkb = int(pl.cdiv(seq_k, block_k))
    if causal:
        # only blocks up to the diagonal contribute (explicit int32 math:
        # x64 weak-typing + Mosaic lowering disagree on int promotion)
        hi = (qi + 1) * jnp.int32(block_q)
        nkb = jnp.minimum(jnp.int32(nkb),
                          lax.div(hi + jnp.int32(block_k - 1),
                                  jnp.int32(block_k)))
    lo = jnp.int32(0)
    if window:
        # sliding window: whole k blocks before the earliest visible
        # key are skipped (this is where the T/window saving comes from)
        lo = _window_lo(qi, block_q, block_k, window)

    neg_big = jnp.float32(-1e30)  # avoid -inf arithmetic in Mosaic

    def body(j, carry):
        o, l, m = carry  # o:[bq,d]  l,m:[bq,1]  (keep 2-D for the VPU)
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        qpos = qi * block_q + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
        kpos = j * block_k + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        mask = kpos < seq_k  # K padding
        if causal:
            mask = mask & (qpos >= kpos)
        if window:
            mask = mask & (qpos - kpos < jnp.int32(window))
        s = jnp.where(mask, s, neg_big)
        new_m = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - new_m), 0.0)
        corr = jnp.exp(m - new_m)
        new_l = l * corr + jnp.sum(p, axis=1, keepdims=True)
        new_o = o * corr + jnp.dot(p, v, preferred_element_type=jnp.float32)
        return new_o, new_l, new_m

    o0 = jnp.zeros((bq, d), jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    m0 = jnp.full((bq, 1), neg_big, jnp.float32)
    # int32 bounds: the package enables jax x64 (f64 NDArray parity), so
    # python-int bounds would make an i64 counter Mosaic cannot lower
    o, l, m = lax.fori_loop(lo, jnp.int32(nkb), body,
                            (o0, l0, m0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (o / l).astype(o_ref.dtype)
    # per-row logsumexp — the backward's residual: p = exp(s - lse)
    # recovers the normalized probabilities blockwise. Kept [T, 1]-shaped
    # (last dim 1): Mosaic requires block last-two-dims (8k, 128k) or
    # equal to the array dims, which (1, block_q) rows would violate.
    lse_ref[0] = m + jnp.log(l)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret, true_tk,
               window=0):
    """q,k,v: [BH, T, D] (T padded to block multiples); true_tk = unpadded
    key length (padded keys are masked out). Returns (o, lse)."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    grid = (bh, tq // block_q)
    return pl.pallas_call(
        functools.partial(_attn_fwd_kernel, block_q=block_q,
                          block_k=block_k, seq_k=true_tk, causal=causal,
                          scale=scale, window=window),
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32)],
        grid=grid,
        # index-map literals as int32: the package enables jax x64, and
        # python-int constants would trace to i64, which Mosaic rejects
        # at func.return
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda b, i: (b, i, np.int32(0))),
            pl.BlockSpec((1, tk, d),
                         lambda b, i: (b, np.int32(0), np.int32(0))),
            pl.BlockSpec((1, tk, d),
                         lambda b, i: (b, np.int32(0), np.int32(0))),
        ],
        out_specs=[pl.BlockSpec((1, block_q, d),
                                lambda b, i: (b, i, np.int32(0))),
                   pl.BlockSpec((1, block_q, 1),
                                lambda b, i: (b, i, np.int32(0)))],
        interpret=interpret,
    )(q, k, v)


def _attn_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dcap_ref, dq_ref,
                    *, block_q, block_k, seq_k, causal, scale, window=0):
    """dQ for one query block: stream over key blocks, recomputing the
    probability block from (q, k, lse) — nothing T×T is ever resident."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)          # [bq, D]
    do = do_ref[0].astype(jnp.float32)        # [bq, D]
    lse = lse_ref[0]                          # [bq, 1]
    dcap = dcap_ref[0]                        # [bq, 1]  rowsum(dO*O)
    bq, d = q.shape
    nkb = int(pl.cdiv(seq_k, block_k))
    if causal:
        hi = (qi + 1) * jnp.int32(block_q)
        nkb = jnp.minimum(jnp.int32(nkb),
                          lax.div(hi + jnp.int32(block_k - 1),
                                  jnp.int32(block_k)))
    lo = jnp.int32(0)
    if window:
        lo = _window_lo(qi, block_q, block_k, window)

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        qpos = qi * block_q + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
        kpos = j * block_k + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        mask = kpos < seq_k
        if causal:
            mask = mask & (qpos >= kpos)
        if window:
            mask = mask & (qpos - kpos < jnp.int32(window))
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dcap) * scale
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((bq, d), jnp.float32)
    dq = lax.fori_loop(lo, jnp.int32(nkb), body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _attn_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dcap_ref,
                     dk_ref, dv_ref, *, block_q, block_k, seq_q, seq_k,
                     causal, scale, window=0):
    """dK/dV for one key block: stream over query blocks."""
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)          # [bk, D]
    v = v_ref[0].astype(jnp.float32)          # [bk, D]
    bk, d = k.shape
    nqb = jnp.int32(int(pl.cdiv(seq_q, block_q)))
    if causal:
        # first query block intersecting the diagonal for this key block
        lo = lax.div(ki * jnp.int32(block_k), jnp.int32(block_q))
    else:
        lo = jnp.int32(0)
    if window:
        # sliding window: the LAST query that can see any key of this
        # block is (ki*block_k + bk - 1) + window - 1; later q blocks
        # are skipped entirely
        nqb = jnp.minimum(
            nqb, lax.div(ki * jnp.int32(block_k)
                         + jnp.int32(block_k + window - 2),
                         jnp.int32(block_q)) + jnp.int32(1))

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :]    # [bq, 1]
        dcap = dcap_ref[0, pl.ds(i * block_q, block_q), :]  # [bq, 1]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        qpos = i * block_q + lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
        kpos = ki * block_k + lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
        mask = (kpos < seq_k) & (qpos < seq_q)
        if causal:
            mask = mask & (qpos >= kpos)
        if window:
            mask = mask & (qpos - kpos < jnp.int32(window))
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dv = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dcap) * scale
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((bk, d), jnp.float32)
    dk, dv = lax.fori_loop(lo, nqb, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, g, causal, scale, block_q, block_k,
               interpret, true_tq, true_tk, window=0):
    """Blockwise flash backward: dQ kernel over query blocks, dK/dV
    kernel over key blocks. Memory is O(T·block), not O(T²)."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    # D_i = sum_d dO_i * O_i  (the softmax-jacobian row term); padded
    # query rows have dO == 0 so their D is 0. [BH, T, 1] like lse.
    dcap = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                   axis=-1, keepdims=True)
    kw = dict(block_q=block_q, block_k=block_k, causal=causal, scale=scale,
              window=window)
    qspec = pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, np.int32(0)))
    kfull = pl.BlockSpec((1, tk, d), lambda b, i: (b, np.int32(0),
                                                   np.int32(0)))
    qfull = pl.BlockSpec((1, tq, d), lambda b, i: (b, np.int32(0),
                                                   np.int32(0)))
    kspec = pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, np.int32(0)))
    rowq = pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, np.int32(0)))
    rowfull = pl.BlockSpec((1, tq, 1), lambda b, i: (b, np.int32(0),
                                                     np.int32(0)))
    dq = pl.pallas_call(
        functools.partial(_attn_dq_kernel, seq_k=true_tk, **kw),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(bh, tq // block_q),
        in_specs=[qspec, kfull, kfull, qspec, rowq, rowq],
        out_specs=qspec,
        interpret=interpret,
    )(q, k, v, g, lse, dcap)
    dk, dv = pl.pallas_call(
        functools.partial(_attn_dkv_kernel, seq_q=true_tq, seq_k=true_tk,
                          **kw),
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        grid=(bh, tk // block_k),
        in_specs=[qfull, kspec, kspec, qfull, rowfull, rowfull],
        out_specs=[kspec, kspec],
        interpret=interpret,
    )(q, k, v, g, lse, dcap)
    return dq, dk, dv


def _reference_attention(q, k, v, causal, scale, true_tk):
    """Blockwise-exact attention in plain JAX — supplies the VJP and the
    numerical oracle. [BH, T, D] layout, f32 accumulation."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    tq, tk = q.shape[1], k.shape[1]
    kpos = lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
    mask = kpos < true_tk
    if causal:
        mask = mask & (lax.broadcasted_iota(jnp.int32, (tq, tk), 0) >= kpos)
    s = jnp.where(mask[None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)  # -inf masked entries -> 0
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash_core(q, k, v, causal, scale, block_q, block_k, interpret,
                true_tq, true_tk, window=0):
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                      true_tk, window)[0]


def _flash_core_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                    true_tq, true_tk, window=0):
    o, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                        interpret, true_tk, window)
    return o, (q, k, v, o, lse)


def _flash_core_bwd(causal, scale, block_q, block_k, interpret, true_tq,
                    true_tk, window, res, g):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, g, causal, scale, block_q, block_k,
                      interpret, true_tq, true_tk, window)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, *, causal=False, scale=None, block_q=None,
                    block_k=None, interpret=None, window=0):
    """Fused attention. q,k,v: [B, T, H, D]; returns [B, T, H, D].

    ``window``>0 (requires ``causal``) computes sliding-window
    attention: keys more than ``window-1`` positions behind their query
    are masked AND whole out-of-window key/query blocks are skipped in
    the forward and both backward kernels, so attention compute scales
    with T·window instead of T².

    Pads T to block multiples internally (padded keys masked out, padded
    queries dropped). Use inside jit; differentiable.

    Block sizes default from ``default_attn_blocks`` (512 for
    head_dim <= 128: bigger tiles amortize the streaming loop, measured
    -33% on the 124M-LM train step vs the round-3 128-blocks,
    doc/performance.md; large head_dims overflow VMEM at 512).
    """
    dq, dk = default_attn_blocks(q.shape[-1])
    if block_q is None:
        block_q = dq
    if block_k is None:
        block_k = dk
    if interpret is None:
        interpret = _use_interpret()
    _count_dispatch()
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    block_q = min(block_q, _round_up(tq, 8))
    block_k = min(block_k, _round_up(tk, 8))

    def to_bh(x, t):
        x = x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        tp = _round_up(t, max(block_q, block_k))
        if tp != t:
            x = jnp.pad(x, ((0, 0), (0, tp - t), (0, 0)))
        return x

    if window < 0:
        # a negative window would mask EVERY key (qpos-kpos >= 0 always)
        # and silently return zeros through the l >= 1e-30 clamp
        raise ValueError("flash_attention: window must be >= 0, got %d"
                         % window)
    if window and not causal:
        raise ValueError("flash_attention: window>0 requires causal")
    qb, kb, vb = to_bh(q, tq), to_bh(k, tk), to_bh(v, tk)
    out = _flash_core(qb, kb, vb, causal, scale, block_q, block_k, interpret,
                      tq, tk, int(window))
    out = out[:, :tq]
    return out.reshape(b, h, tq, d).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# striped pair attention — the half-block kernel for striped ring
# attention (parallel/ring.py striped_ring_attention).
#
# Under the STRIPED sequence layout, ring device ``my`` holds tokens at
# global positions {a*n + my}; at each hop it attends its queries against
# the K/V block of ring position ``src`` (tokens {b*n + src}). The causal
# mask is then a*n + q_off >= b*n + k_off — a near-triangle for EVERY
# (my, src) pair, so per-hop FLOPs are balanced across the ring (striped
# attention), unlike the contiguous layout where device 0 masks almost
# everything and device n-1 almost nothing. These kernels skip key
# blocks entirely above the position diagonal (the dynamic fori bound),
# so each hop really costs ~half a block, and emit/consume the per-row
# logsumexp so partial results merge exactly via streaming softmax.
# (q_off, k_off) arrive as an SMEM scalar operand — they are traced ring
# indices, different on every device and hop.


def _spair_fwd_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      block_q, block_k, seq_k, n_stride, scale):
    qi = pl.program_id(1)
    q_off = offs_ref[0]
    k_off = offs_ref[1]
    q = q_ref[0].astype(jnp.float32)  # [bq, D]
    bq, d = q.shape
    ns = jnp.int32(n_stride)
    nkb_static = int(pl.cdiv(seq_k, block_k))
    # last key block with any valid pair: max qpos >= min kpos
    numer = ((qi + 1) * jnp.int32(block_q) - 1) * ns + q_off - k_off
    nkb = jnp.minimum(jnp.int32(nkb_static),
                      lax.div(numer, jnp.int32(block_k) * ns) + 1)
    nkb = jnp.maximum(nkb, jnp.int32(0))
    neg_big = jnp.float32(-1e30)

    def body(j, carry):
        o, l, m = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        qrow = qi * block_q + lax.broadcasted_iota(jnp.int32,
                                                   (bq, block_k), 0)
        kcol = j * block_k + lax.broadcasted_iota(jnp.int32,
                                                  (bq, block_k), 1)
        mask = (kcol < seq_k) & (qrow * ns + q_off >= kcol * ns + k_off)
        s = jnp.where(mask, s, neg_big)
        new_m = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - new_m), 0.0)
        corr = jnp.exp(m - new_m)
        new_l = l * corr + jnp.sum(p, axis=1, keepdims=True)
        new_o = o * corr + jnp.dot(p, v,
                                   preferred_element_type=jnp.float32)
        return new_o, new_l, new_m

    o0 = jnp.zeros((bq, d), jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    m0 = jnp.full((bq, 1), neg_big, jnp.float32)
    o, l, m = lax.fori_loop(jnp.int32(0), nkb, body, (o0, l0, m0))
    # rows with no valid keys (l == 0): o = 0, lse = -big so the merge
    # weights them to zero
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), neg_big)
    o_ref[0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[0] = lse


def _spair_dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                     dcap_ref, dq_ref, *, block_q, block_k, seq_k,
                     n_stride, scale):
    qi = pl.program_id(1)
    q_off = offs_ref[0]
    k_off = offs_ref[1]
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]
    dcap = dcap_ref[0]
    bq, d = q.shape
    ns = jnp.int32(n_stride)
    nkb_static = int(pl.cdiv(seq_k, block_k))
    numer = ((qi + 1) * jnp.int32(block_q) - 1) * ns + q_off - k_off
    nkb = jnp.minimum(jnp.int32(nkb_static),
                      lax.div(numer, jnp.int32(block_k) * ns) + 1)
    nkb = jnp.maximum(nkb, jnp.int32(0))

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        qrow = qi * block_q + lax.broadcasted_iota(jnp.int32,
                                                   (bq, block_k), 0)
        kcol = j * block_k + lax.broadcasted_iota(jnp.int32,
                                                  (bq, block_k), 1)
        mask = (kcol < seq_k) & (qrow * ns + q_off >= kcol * ns + k_off)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dcap) * scale
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((bq, d), jnp.float32)
    dq = lax.fori_loop(jnp.int32(0), nkb, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _spair_dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      dcap_ref, dk_ref, dv_ref, *, block_q, block_k,
                      seq_q, seq_k, n_stride, scale):
    ki = pl.program_id(1)
    q_off = offs_ref[0]
    k_off = offs_ref[1]
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    bk, d = k.shape
    ns = jnp.int32(n_stride)
    nqb = jnp.int32(int(pl.cdiv(seq_q, block_q)))
    # first query block with any valid pair: max kpos <= max qpos in blk
    # a valid iff a*ns + q_off >= ki*block_k*ns + k_off
    amin = ki * jnp.int32(block_k) + \
        jnp.where(k_off > q_off, jnp.int32(1), jnp.int32(0))
    lo = jnp.maximum(lax.div(amin, jnp.int32(block_q)), jnp.int32(0))

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :]
        dcap = dcap_ref[0, pl.ds(i * block_q, block_q), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        qrow = i * block_q + lax.broadcasted_iota(jnp.int32,
                                                  (block_q, bk), 0)
        kcol = ki * block_k + lax.broadcasted_iota(jnp.int32,
                                                   (block_q, bk), 1)
        mask = (kcol < seq_k) & (qrow < seq_q) & \
            (qrow * ns + q_off >= kcol * ns + k_off)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dv = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dcap) * scale
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((bk, d), jnp.float32)
    dk, dv = lax.fori_loop(lo, nqb, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _spair_specs(tq, tk, block_q, d):
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    qspec = pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, np.int32(0)))
    kfull = pl.BlockSpec((1, tk, d), lambda b, i: (b, np.int32(0),
                                                   np.int32(0)))
    rowq = pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, np.int32(0)))
    return smem, qspec, kfull, rowq


def _spair_fwd(q, k, v, offs, n_stride, scale, block_q, block_k,
               interpret, true_tk):
    bh, tq, d = q.shape
    tk = k.shape[1]
    smem, qspec, kfull, rowq = _spair_specs(tq, tk, block_q, d)
    return pl.pallas_call(
        functools.partial(_spair_fwd_kernel, block_q=block_q,
                          block_k=block_k, seq_k=true_tk,
                          n_stride=n_stride, scale=scale),
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32)],
        grid=(bh, tq // block_q),
        in_specs=[smem, qspec, kfull, kfull],
        out_specs=[qspec, rowq],
        interpret=interpret,
    )(offs, q, k, v)


def _spair_bwd_impl(q, k, v, o, lse, offs, g_o, g_lse, n_stride, scale,
                    block_q, block_k, interpret, true_tq, true_tk):
    bh, tq, d = q.shape
    tk = k.shape[1]
    # softmax-jacobian row term, with the lse cotangent folded in:
    # ds = p*(dp - D) + g_lse*p  ==  p*(dp - (D - g_lse))
    dcap = jnp.sum(g_o.astype(jnp.float32) * o.astype(jnp.float32),
                   axis=-1, keepdims=True) - g_lse.astype(jnp.float32)
    smem, qspec, kfull, rowq = _spair_specs(tq, tk, block_q, d)
    qfull = pl.BlockSpec((1, tq, d), lambda b, i: (b, np.int32(0),
                                                   np.int32(0)))
    kspec = pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, np.int32(0)))
    rowfull = pl.BlockSpec((1, tq, 1), lambda b, i: (b, np.int32(0),
                                                     np.int32(0)))
    kw = dict(block_q=block_q, block_k=block_k, n_stride=n_stride,
              scale=scale)
    dq = pl.pallas_call(
        functools.partial(_spair_dq_kernel, seq_k=true_tk, **kw),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(bh, tq // block_q),
        in_specs=[smem, qspec, kfull, kfull, qspec, rowq, rowq],
        out_specs=qspec,
        interpret=interpret,
    )(offs, q, k, v, g_o, lse, dcap)
    dk, dv = pl.pallas_call(
        functools.partial(_spair_dkv_kernel, seq_q=true_tq,
                          seq_k=true_tk, **kw),
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        grid=(bh, tk // block_k),
        in_specs=[smem, qfull, kspec, kspec, qfull, rowfull, rowfull],
        out_specs=[kspec, kspec],
        interpret=interpret,
    )(offs, q, k, v, g_o, lse, dcap)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _spair_core(q, k, v, offs, n_stride, scale, block_q, block_k,
                interpret, true_tk):
    return _spair_fwd(q, k, v, offs, n_stride, scale, block_q, block_k,
                      interpret, true_tk)


def _spair_core_fwd(q, k, v, offs, n_stride, scale, block_q, block_k,
                    interpret, true_tk):
    o, lse = _spair_fwd(q, k, v, offs, n_stride, scale, block_q, block_k,
                        interpret, true_tk)
    return (o, lse), (q, k, v, o, lse, offs)


def _spair_core_bwd(n_stride, scale, block_q, block_k, interpret, true_tk,
                    res, gs):
    q, k, v, o, lse, offs = res
    g_o, g_lse = gs
    tq = q.shape[1]
    dq, dk, dv = _spair_bwd_impl(q, k, v, o, lse, offs, g_o, g_lse,
                                 n_stride, scale, block_q, block_k,
                                 interpret, tq, true_tk)
    d_offs = np.zeros(offs.shape, jax.dtypes.float0)
    return dq, dk, dv, d_offs


_spair_core.defvjp(_spair_core_fwd, _spair_core_bwd)


def striped_pair_attention(q, k, v, q_off, k_off, *, n_stride, scale=None,
                           block_q=128, block_k=128, interpret=None):
    """One striped ring hop: flash attention of the local query block
    against one arriving K/V block under the striped causal mask
    ``(a*n + q_off) >= (b*n + k_off)``.

    q, k, v: [BH, C, D] (C = T/n local length; C must divide into the
    block sizes after internal clamping). ``q_off``/``k_off``: traced
    int32 ring positions. Returns ``(o, lse)`` — o normalized over the
    VALID keys of this block, lse the per-row logsumexp (-1e30 where no
    key is valid) — merge partials with ``jnp.logaddexp`` streaming
    softmax. Differentiable (custom_vjp; the lse cotangent folds into
    the flash backward's dcap term).
    """
    if interpret is None:
        interpret = _use_interpret()
    _count_dispatch()
    bh, tq, d = q.shape
    tk = k.shape[1]
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    block_q = min(block_q, _round_up(tq, 8))
    block_k = min(block_k, _round_up(tk, 8))

    def padt(x, t, blk):
        tp = _round_up(t, blk)
        if tp != t:
            x = jnp.pad(x, ((0, 0), (0, tp - t), (0, 0)))
        return x

    qp = padt(q, tq, block_q)
    kp, vp = padt(k, tk, block_k), padt(v, tk, block_k)
    offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])
    o, lse = _spair_core(qp, kp, vp, offs, int(n_stride), float(scale),
                         block_q, block_k, interpret, tk)
    return o[:, :tq], lse[:, :tq]


# ---------------------------------------------------------------------------
# fused GEMM epilogue (matmul + per-column scale/bias + activation)

_ACTS = {
    "linear": lambda x: x,
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "gelu": jax.nn.gelu,
}

# derivative of the activation expressed from its OUTPUT (residual-free
# backward); gelu is excluded (needs the preactivation) and handled by
# composing the linear kernel with XLA's gelu
_ACT_GRADS = {
    "linear": lambda g, out: g,
    "relu": lambda g, out: g * (out > 0),
    "sigmoid": lambda g, out: g * out * (1 - out),
    "tanh": lambda g, out: g * (1 - out * out),
}


def _gemm_epi_kernel(x_ref, w_ref, s_ref, b_ref, o_ref, acc_ref, *, act,
                     nk):
    """One (M,N) tile of act(scale * (x@w) + bias): K is the innermost
    grid dim, accumulated in a VMEM f32 scratch; the epilogue runs on the
    accumulator while it is still in VMEM — one HBM round-trip for the
    output instead of one per fused op."""
    kidx = pl.program_id(2)

    @pl.when(kidx == 0)
    def _init():
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    acc_ref[...] += jnp.dot(x_ref[...], w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(kidx == jnp.int32(nk - 1))
    def _epilogue():
        acc = acc_ref[...]
        acc = acc * s_ref[...].astype(jnp.float32) \
            + b_ref[...].astype(jnp.float32)
        o_ref[...] = _ACTS[act](acc).astype(o_ref.dtype)


def _matmul_epilogue(x, w, scale, bias, act, block_m, block_n, block_k,
                     interpret):
    """act(scale * (x @ w) + bias); x [M,K], w [K,N], scale/bias [N] or
    None. K-blocked Pallas GEMM with the epilogue fused on the MXU
    accumulator."""
    m, kdim = x.shape
    n = w.shape[1]
    bm = min(block_m, _round_up(m, 8))
    bn = min(block_n, _round_up(n, 128))
    bk = min(block_k, _round_up(kdim, 128))
    mp, np_, kp = _round_up(m, bm), _round_up(n, bn), _round_up(kdim, bk)
    xp = jnp.pad(x, ((0, mp - m), (0, kp - kdim))) \
        if (mp, kp) != (m, kdim) else x
    wp = jnp.pad(w, ((0, kp - kdim), (0, np_ - n))) \
        if (kp, np_) != (kdim, n) else w
    if scale is None:
        scale = jnp.ones((n,), jnp.float32)
    if bias is None:
        bias = jnp.zeros((n,), jnp.float32)
    sp = jnp.pad(scale, (0, np_ - n)).reshape(1, np_)
    bp = jnp.pad(bias, (0, np_ - n)).reshape(1, np_)
    nk = kp // bk
    out = pl.pallas_call(
        functools.partial(_gemm_epi_kernel, act=act, nk=nk),
        out_shape=jax.ShapeDtypeStruct((mp, np_), x.dtype),
        grid=(mp // bm, np_ // bn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (np.int32(0), j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (np.int32(0), j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(xp, wp, sp, bp)
    return out[:m, :n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fused_linear_core(x, w, b, act, block_m, block_n, block_k, interpret):
    return _matmul_epilogue(x, w, None, b, act, block_m, block_n, block_k,
                            interpret)


def _fused_linear_fwd(x, w, b, act, block_m, block_n, block_k, interpret):
    out = _matmul_epilogue(x, w, None, b, act, block_m, block_n, block_k,
                           interpret)
    return out, (x, w, out)


def _fused_linear_bwd(act, block_m, block_n, block_k, interpret, res, g):
    x, w, out = res
    dpre = _ACT_GRADS[act](g.astype(jnp.float32), out.astype(jnp.float32))
    dpre = dpre.astype(x.dtype)
    # the backward matmuls are plain MXU dots — XLA schedules them
    dx = jnp.dot(dpre, w.T)
    dw = jnp.dot(x.T, dpre)
    db = jnp.sum(dpre, axis=0)
    return dx, dw, db


_fused_linear_core.defvjp(_fused_linear_fwd, _fused_linear_bwd)


def fused_linear(x, w, b, act="linear", *, block_m=256, block_n=256,
                 block_k=512, interpret=None):
    """act(x @ w + b) in one kernel. x: [M, K], w: [K, N], b: [N].

    Differentiable (``jax.custom_vjp``; the activation derivative is
    reconstructed from the output, so no extra residuals are kept).
    The reference fuses this per-op inside mshadow expressions
    (``fully_connected-inl.h:53-81`` + activation); on TPU the epilogue
    runs on the MXU accumulator while it is still in VMEM.
    """
    if interpret is None:
        interpret = _use_interpret()
    _count_dispatch()
    if act not in _ACTS:
        raise ValueError("unknown activation %r" % act)
    if act == "gelu":
        # gelu'(x) needs the preactivation: compose the fused linear
        # kernel with XLA's gelu (still one GEMM + one fused elementwise)
        pre = _fused_linear_core(x, w, b, "linear", block_m, block_n,
                                 block_k, interpret)
        return jax.nn.gelu(pre)
    return _fused_linear_core(x, w, b, act, block_m, block_n, block_k,
                              interpret)


def fused_conv_bn_act(x, w, scale, bias, stride=(1, 1), pad=(0, 0),
                      dilate=(1, 1), act="relu", *, block_m=256,
                      block_n=256, block_k=512, interpret=None):
    """``act(scale_c * conv(x, w) + bias_c)`` — the cuDNN-analogue fused
    inference kernel (reference selects ``cudnn_convolution-inl.h`` /
    ``cudnn_batch_norm-inl.h`` at CreateOp; here conv, the folded
    BatchNorm affine, and the activation run as ONE Pallas GEMM).

    x [N,C,H,W], w [O,C,kh,kw], scale/bias [O] (fold BatchNorm moving
    stats and any conv bias into them). im2col is XLA's
    ``conv_general_dilated_patches``; the GEMM + epilogue is Pallas.
    """
    if interpret is None:
        interpret = _use_interpret()
    _count_dispatch()
    n, c, h, wdim = x.shape
    nf, _, kh, kw = w.shape
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), tuple(stride),
        ((int(pad[0]),) * 2, (int(pad[1]),) * 2),
        rhs_dilation=tuple(dilate),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    nb, ckk, oh, ow = patches.shape
    xm = patches.transpose(0, 2, 3, 1).reshape(nb * oh * ow, ckk)
    wm = w.reshape(nf, ckk).T
    out = _matmul_epilogue(xm, wm, scale, bias, act, block_m, block_n,
                           block_k, interpret)
    return out.reshape(nb, oh, ow, nf).transpose(0, 3, 1, 2)


# ---------------------------------------------------------------------------
# training conv(1x1)+BN-stats epilogue: GEMM that also emits per-column
# sum and sum-of-squares of its OWN output, accumulated while the MXU
# tile is still in VMEM — the batch-stats read the training BatchNorm
# would otherwise do against HBM disappears. Reference analogue: the
# cuDNN-selected conv + batch_norm pair (cudnn_convolution-inl.h,
# batch_norm-inl.h:95-125), fused the TPU way.

def _gemm_stats_kernel(x_ref, w_ref, o_ref, s1_ref, s2_ref, acc_ref, *,
                       nk):
    """One (M,N) tile of x@w; on the last K step also reduce the f32
    accumulator tile to per-column sum / sum-of-squares partials
    (grid_m x N), BEFORE the output is rounded to its storage dtype —
    the stats see the exact f32 GEMM results."""
    kidx = pl.program_id(2)

    @pl.when(kidx == 0)
    def _init():
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    acc_ref[...] += jnp.dot(x_ref[...], w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(kidx == jnp.int32(nk - 1))
    def _epilogue():
        acc = acc_ref[...]
        o_ref[...] = acc.astype(o_ref.dtype)
        # Mosaic wants >=8 sublanes per output block: broadcast the
        # per-column partials over the 8 rows (the host-side combine
        # divides the final sum by 8 — exact in binary fp)
        s1 = jnp.sum(acc, axis=0, keepdims=True)
        s2 = jnp.sum(acc * acc, axis=0, keepdims=True)
        s1_ref[...] = jnp.broadcast_to(s1, s1_ref.shape)
        s2_ref[...] = jnp.broadcast_to(s2, s2_ref.shape)


def _matmul_stats_impl(x, w, block_m, block_n, block_k, interpret):
    m, kdim = x.shape
    n = w.shape[1]
    bm = min(block_m, _round_up(m, 8))
    bn = min(block_n, _round_up(n, 128))
    bk = min(block_k, _round_up(kdim, 128))
    mp, np_, kp = _round_up(m, bm), _round_up(n, bn), _round_up(kdim, bk)
    xp = jnp.pad(x, ((0, mp - m), (0, kp - kdim))) \
        if (mp, kp) != (m, kdim) else x
    wp = jnp.pad(w, ((0, kp - kdim), (0, np_ - n))) \
        if (kp, np_) != (kdim, n) else w
    nk = kp // bk
    gm = mp // bm
    out, s1p, s2p = pl.pallas_call(
        functools.partial(_gemm_stats_kernel, nk=nk),
        out_shape=(jax.ShapeDtypeStruct((mp, np_), x.dtype),
                   jax.ShapeDtypeStruct((gm * 8, np_), jnp.float32),
                   jax.ShapeDtypeStruct((gm * 8, np_), jnp.float32)),
        grid=(gm, np_ // bn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
        ],
        out_specs=(pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
                   pl.BlockSpec((8, bn), lambda i, j, k: (i, j)),
                   pl.BlockSpec((8, bn), lambda i, j, k: (i, j))),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(xp, wp)
    # tiny (8*grid_m, N) partial reduction — each tile's partial is
    # replicated over 8 sublanes (Mosaic min block), hence the /8,
    # which is exact in binary fp; padded M rows are zeros in x, so
    # they contribute exactly 0 to both partials
    return (out[:m, :n], s1p.sum(axis=0)[:n] / 8.0,
            s2p.sum(axis=0)[:n] / 8.0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _matmul_stats_core(x, w, block_m, block_n, block_k, interpret):
    return _matmul_stats_impl(x, w, block_m, block_n, block_k, interpret)


def _matmul_stats_fwd(x, w, block_m, block_n, block_k, interpret):
    outs = _matmul_stats_impl(x, w, block_m, block_n, block_k, interpret)
    return outs, (x, w, outs[0])


def _matmul_stats_bwd(block_m, block_n, block_k, interpret, res, gs):
    x, w, y = res
    gy, gs1, gs2 = gs
    # s1 = sum_rows(y), s2 = sum_rows(y^2): their cotangents fold into
    # the output cotangent as broadcasts, keeping ONE pair of backward
    # MXU dots for the whole fused op
    g = (gy.astype(jnp.float32)
         + gs1[None, :].astype(jnp.float32)
         + 2.0 * y.astype(jnp.float32) * gs2[None, :].astype(jnp.float32))
    g = g.astype(x.dtype)
    dx = jnp.dot(g, w.T)
    dw = jnp.dot(x.T, g)
    return dx, dw


_matmul_stats_core.defvjp(_matmul_stats_fwd, _matmul_stats_bwd)


def matmul_stats(x, w, *, block_m=256, block_n=256, block_k=512,
                 interpret=None):
    """(x @ w, per-column sum, per-column sum-of-squares) in ONE kernel.

    x: [M, K], w: [K, N]. The stats are exact f32 sums of the GEMM
    output read from the VMEM accumulator — the consumer (training
    conv+BatchNorm fusion, ops/fusion.py) derives batch mean/var
    without re-reading the activation from HBM. Differentiable:
    d(s1)/d(s2) cotangents fold into the output cotangent, so the
    backward is the usual two MXU dots."""
    if interpret is None:
        interpret = _use_interpret()
    _count_dispatch()
    return _matmul_stats_core(x, w, block_m, block_n, block_k, interpret)


# ---------------------------------------------------------------------------
# paged attention — the serving engine's decode/verify read (ISSUE 11).
#
# The slot-paged KV cache is [S, max_len, Hkv, D] with every slot at its
# own position; the dense read gathers (and, for int8, dequantizes) ALL
# max_len rows per emitted token even when a slot is 40 tokens into a
# 1024-row cache. This kernel walks only each slot's LIVE blocks: grid
# over (slot, kv-head, kv-block) under a PrefetchScalarGridSpec — the
# per-slot position vector is scalar-prefetched so the cache index
# maps clamp every grid step past ceil((pos + C) / block_k) back to
# the slot's last live block (a revisited block index, whose HBM->VMEM
# copy Mosaic elides; the body is pl.when-gated off), i.e. the bound
# cuts the DMA itself, not just the compute. Online-softmax scratch
# accumulation merges blocks exactly (a reassociation, not an
# approximation — the same argument as Decoder._blocked_attn), and
# int8 caches dequantize per block IN the kernel from the side-scale
# operands, so the cache is read once at 1 byte/elem instead of being
# materialized as a full float copy first. C > 1 serves the chunked-query flavors: the
# speculative verify step's [S, K+1] chunk and the draft model's
# catch-up window (doc/serving.md "Paged attention").
#
# NOT ring-safe: a windowed ring stores rows at wrapped positions, so
# "rows [0, pos+C)" is not the live set — the engine refuses loudly and
# serves those models with the exact dense ring walk (UserWarning
# precedent: speculation, prefix cache).


def default_paged_block_k(max_len):
    """KV rows per block for ``paged_attention``: the largest of
    (128, 64, 32, 16, 8) dividing ``max_len`` (whole blocks keep the
    in-kernel slices static), else ``max_len`` itself — a cache too
    short/odd to block degenerates to one block, still bounded by the
    position mask. ``MXNET_PAGED_BLOCK_K`` overrides."""
    import os
    override = os.environ.get("MXNET_PAGED_BLOCK_K")
    if override:
        b = int(override)
        # validate HERE, naming the knob: an unvalidated 0/negative
        # dies later inside a jitted serving trace (ZeroDivisionError
        # at the divisibility check; negative iota shapes in Pallas)
        # with no pointer back to the env var
        if b <= 0 or max_len % b:
            raise ValueError(
                "MXNET_PAGED_BLOCK_K=%s must be a positive divisor of "
                "the cache length %d" % (override, max_len))
        return b
    for b in (128, 64, 32, 16, 8):
        if max_len % b == 0:
            return b
    return max_len


def _paged_attn_kernel(pos_ref, q_ref, k_ref, v_ref, *rest, block_k,
                       chunk, n_blocks, scale, quant):
    """One (slot, kv-head, kv-block) grid cell of the paged read.

    The kv-block axis is a GRID dimension, not an in-kernel loop, so
    the per-slot bound cuts the DMA itself: the cache BlockSpecs'
    index maps (see ``paged_attention``) send every dead step back to
    the slot's last live block — an unchanged block index, whose copy
    Mosaic elides — and this body is ``pl.when``-gated off for them.
    Online-softmax state (acc/l/m) lives in VMEM scratch carried
    across the innermost grid sweep; the output block is written once,
    on the final step. q block [G*C, D] (the kv head's G query heads x
    C chunk rows, row r = g*C + c — the decoder's GQA fold order);
    int8 caches dequantize per block from the row-scale operands.
    int32 arithmetic throughout (the package enables x64 — see the
    flash kernel's Mosaic i64 notes)."""
    if quant:
        ks_ref, vs_ref, o_ref, acc_ref, l_ref, m_ref = rest
    else:
        o_ref, acc_ref, l_ref, m_ref = rest
    s = pl.program_id(0)
    j = pl.program_id(2)
    p = pos_ref[s]
    nkb = jnp.minimum(
        lax.div(p + jnp.int32(chunk + block_k - 1), jnp.int32(block_k)),
        jnp.int32(n_blocks))
    neg_big = jnp.float32(-1e30)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        m_ref[...] = jnp.full(m_ref.shape, neg_big, jnp.float32)

    @pl.when(j < nkb)
    def _block():
        q = q_ref[0, 0].astype(jnp.float32)      # [G*C, D]
        rows = q.shape[0]
        kb = k_ref[0, :, 0, :]
        vb = v_ref[0, :, 0, :]
        if quant:
            # in-kernel dequant: int8 rows x [bk, 1] f32 row scales —
            # the same arithmetic as Decoder._read_cache, minus the
            # full-cache float materialization
            kb = kb.astype(jnp.float32) * ks_ref[0, :, 0, :]
            vb = vb.astype(jnp.float32) * vs_ref[0, :, 0, :]
        else:
            kb = kb.astype(jnp.float32)
            vb = vb.astype(jnp.float32)
        sc = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) \
            * scale
        # query absolute positions: row r sits at chunk offset r % C
        qpos = p + lax.rem(
            lax.broadcasted_iota(jnp.int32, (rows, block_k), 0),
            jnp.int32(chunk))
        kpos = j * block_k + lax.broadcasted_iota(
            jnp.int32, (rows, block_k), 1)
        mask = kpos <= qpos              # causal; also masks the tail
        sc = jnp.where(mask, sc, neg_big)
        m = m_ref[...]
        new_m = jnp.maximum(m, jnp.max(sc, axis=1, keepdims=True))
        pexp = jnp.where(mask, jnp.exp(sc - new_m), 0.0)
        corr = jnp.exp(m - new_m)
        l_ref[...] = l_ref[...] * corr \
            + jnp.sum(pexp, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr \
            + jnp.dot(pexp, vb, preferred_element_type=jnp.float32)
        m_ref[...] = new_m

    # row `pos` was written before the read, so block 0 always holds a
    # valid key: the denominator is never the clamp
    @pl.when(j == jnp.int32(n_blocks - 1))
    def _emit():
        o_ref[0, 0] = (acc_ref[...]
                       / jnp.maximum(l_ref[...], 1e-30)
                       ).astype(o_ref.dtype)


def paged_attention(q, k, v, pos, *, k_scale=None, v_scale=None,
                    scale=None, block_k=None, interpret=None):
    """Slot-paged decode attention reading only the live KV rows.

    q: [S, C, H, D] — each slot's C-token query chunk (C=1 plain
    decode; C=K+1 the speculative verify chunk; C=W the draft
    catch-up). k, v: [S, L, Hkv, D] cache buffers (float, or int8 with
    ``k_scale``/``v_scale`` [S, L, Hkv] f32 row scales — dequantized
    inside the kernel). pos: [S] int32, the chunk's start position per
    slot: the chunk rows at [pos, pos+C) must already be WRITTEN (the
    decoder writes before reading, same as the dense path), and each
    query row attends keys [0, pos + its chunk offset]. Returns
    [S, C, H, D] in q's dtype, f32 accumulation.

    The kv-block walk is a grid dimension under a
    ``PrefetchScalarGridSpec``: ``pos`` is scalar-prefetched, so the
    cache index maps can clamp every step past a slot's live prefix
    back to its last live block — a REVISITED block index whose
    HBM->VMEM copy Mosaic elides — and the kernel body is
    ``pl.when``-gated off there. Dead rows are therefore never
    FETCHED, not merely never computed on (the distinction the dense
    read and a naive full-plane BlockSpec both miss). Grouped-query
    attention is native: each (slot, kv-head) pair streams one set of
    K/V blocks past the kv head's whole query group. On TPU the
    kernel runs compiled; on CPU (tests, the smoke bench) it runs
    under the Pallas interpreter — same testing discipline as the
    flash kernel above. NOTE the interpreter executes all
    ``n_blocks`` grid steps (the revisit elision is a Mosaic
    behavior), so CPU wall clock and XLA cost analysis both
    under-sell the bound; doc/performance.md records the honest
    smoke metrics."""
    if interpret is None:
        interpret = _use_interpret()
    _count_dispatch()
    s_, c, h, d = q.shape
    l_ = k.shape[1]
    kv = k.shape[2]
    g = h // kv
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    if block_k is None:
        block_k = default_paged_block_k(l_)
    if l_ % block_k:
        raise ValueError(
            "paged_attention: block_k=%d must divide the cache length "
            "%d (whole blocks keep the grid static)" % (block_k, l_))
    quant = (k_scale is not None) or (v_scale is not None)
    if quant and (k_scale is None or v_scale is None):
        raise ValueError("paged_attention: k_scale and v_scale must be "
                         "passed together")
    nb = l_ // block_k
    pos = jnp.asarray(pos, jnp.int32)
    # [S, C, H, D] -> [S, KV, G*C, D]: the head axis splits (kv, g),
    # matching the decoder's GQA fold q.reshape(b, c, kv, g, d)
    qg = q.transpose(0, 2, 1, 3).reshape(s_, kv, g, c, d) \
        .reshape(s_, kv, g * c, d)

    def live_j(si, j, pref):
        # dead grid steps revisit the slot's LAST live block (same
        # block index as the previous step -> Mosaic skips the copy;
        # the kernel body is pl.when-gated off for them)
        p = pref[si]
        nkb = jnp.minimum(
            lax.div(p + jnp.int32(c + block_k - 1),
                    jnp.int32(block_k)),
            jnp.int32(nb))
        return jnp.minimum(j, nkb - 1)

    def qmap(si, hi, j, pref):
        return (si, hi, np.int32(0), np.int32(0))

    def kmap(si, hi, j, pref):
        return (si, live_j(si, j, pref), hi, np.int32(0))

    in_specs = [
        pl.BlockSpec((1, 1, g * c, d), qmap),
        pl.BlockSpec((1, block_k, 1, d), kmap),
        pl.BlockSpec((1, block_k, 1, d), kmap),
    ]
    operands = [qg, k, v]
    if quant:
        # scales ride as [S, L, KV, 1] so the in-kernel block is a
        # 2-D [bk, 1] tile (Mosaic-friendly; broadcasts over D)
        operands.append(k_scale.astype(jnp.float32)[..., None])
        operands.append(v_scale.astype(jnp.float32)[..., None])
        sspec = pl.BlockSpec((1, block_k, 1, 1), kmap)
        in_specs.extend([sspec, sspec])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s_, kv, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, g * c, d), qmap),
        scratch_shapes=[
            pltpu.VMEM((g * c, d), jnp.float32),   # acc
            pltpu.VMEM((g * c, 1), jnp.float32),   # l
            pltpu.VMEM((g * c, 1), jnp.float32),   # m
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_attn_kernel, block_k=block_k, chunk=c,
                          n_blocks=nb, scale=float(scale), quant=quant),
        out_shape=jax.ShapeDtypeStruct((s_, kv, g * c, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(pos, *operands)
    return out.reshape(s_, kv, g, c, d).reshape(s_, h, c, d) \
        .transpose(0, 2, 1, 3)


# -- fused quantized matmuls (ISSUE 17) -------------------------------

def _unpack4_block(u):
    """Unpack a [rows, E/2] uint8 nibble-packed block to f32
    [rows, E]: low nibble = even element, high nibble = odd,
    sign-extended two's complement — the in-VMEM mirror of
    serving.quant.unpack_int4 (kept bitwise in step with it: the
    pallas-vs-fori identity tests pin the pair)."""
    lo = (u & 0xF).astype(jnp.int32)
    hi = ((u >> 4) & 0xF).astype(jnp.int32)
    both = jnp.stack([lo, hi], axis=-1).reshape(
        u.shape[:-1] + (2 * u.shape[-1],))
    return (both - 16 * (both >= 8)).astype(jnp.float32)


def _dequant_w(w_ref, s_ref, bits, group):
    """Dequantize one weight tile in VMEM. int4: unpack + per-group
    contraction-axis scales (must precede the dot). int8: raw cast —
    the per-row scale folds into the OUTPUT (callers multiply the
    accumulator by ``s^T`` instead, exactly like the fori fallback)."""
    if bits == 4:
        v = _unpack4_block(w_ref[...])
        return v * jnp.repeat(s_ref[...], group, axis=-1)
    return w_ref[...].astype(jnp.float32)


def _quant_mm_kernel(x_ref, w_ref, s_ref, o_ref, *, bits, group):
    w = _dequant_w(w_ref, s_ref, bits, group)
    acc = lax.dot_general(x_ref[...], w, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    if bits == 8:
        acc = acc * jnp.transpose(s_ref[...])
    o_ref[...] = acc.astype(o_ref.dtype)


def quant_matmul(x, q, scale, *, bits=8, group=None, block_f=None,
                 out_dtype=None, interpret=None):
    """``x [M, E] @ dequant(q) [F, E]^T -> [M, F]``: the Pallas
    scale-fused matmul for quantized serving weights.

    The grid walks OUTPUT-CHANNEL blocks only — each step streams one
    ``[block_f, E]`` quantized tile into VMEM, dequantizes it there
    (int8: cast, scale folded into the product after the dot; int4:
    unpack nibbles + per-group contraction scales before the dot) and
    contracts the full E axis. Blocking over output channels is a
    PARTITION of independent dots, never a reassociation — on f32
    inputs the result is bitwise identical to
    ``serving.quant.scale_fused_matmul``'s ``fori_loop`` at any block
    size, which is what lets ``matmul_impl="pallas"`` keep the
    engine's byte-identity gauntlet intact. The compiled program
    reads the stored int8/packed-int4 stream plus one tile of float
    staging (the ``bytes_accessed`` story, now at kernel granularity).

    ``q``: int8 ``[F, E]`` (``bits=8``, ``scale`` f32 ``[F]``) or
    nibble-packed uint8 ``[F, E//2]`` (``bits=4``, ``scale`` f32
    ``[F, E//group]``). ``block_f`` must divide F (callers pass the
    ``MXNET_QUANT_CHUNK``-resolved chunk so both impls stage
    identically); default: largest of (256..8) dividing F, else F.
    On CPU the kernel runs under the Pallas interpreter (tests)."""
    if interpret is None:
        interpret = _use_interpret()
    _count_dispatch()
    m, e = x.shape
    f = q.shape[0]
    ew = q.shape[1]
    if bits == 4:
        if group is None or (2 * ew) % group:
            raise ValueError(
                "quant_matmul: bits=4 needs the per-group scale width "
                "(an even divisor of E=%d), got group=%r"
                % (2 * ew, group))
        s2 = scale
    else:
        s2 = scale.reshape(f, 1)
    if block_f is None:
        for r in (256, 128, 64, 32, 16, 8):
            if f % r == 0:
                block_f = r
                break
        else:
            block_f = f
    block_f = min(block_f, f)
    if f % block_f:
        raise ValueError(
            "quant_matmul: block_f=%d must divide the output-channel "
            "count %d (the grid partitions whole blocks)"
            % (block_f, f))
    mp = m if interpret else _round_up(m, 8)
    xp = x if mp == m else jnp.pad(x, ((0, mp - m), (0, 0)))
    sw = s2.shape[1]
    bf = block_f
    out = pl.pallas_call(
        functools.partial(_quant_mm_kernel, bits=bits, group=group),
        out_shape=jax.ShapeDtypeStruct(
            (mp, f), jnp.dtype(out_dtype) if out_dtype else x.dtype),
        grid=(int(f // bf),),
        in_specs=[
            pl.BlockSpec((mp, e), lambda i: (np.int32(0), np.int32(0))),
            pl.BlockSpec((bf, ew), lambda i: (i, np.int32(0))),
            pl.BlockSpec((bf, sw), lambda i: (i, np.int32(0))),
        ],
        out_specs=pl.BlockSpec((mp, bf), lambda i: (np.int32(0), i)),
        interpret=interpret,
    )(xp, q, s2)
    return out[:m]


def _fused_decode_kernel(pos_ref, x_ref, k_ref, v_ref, wq_ref, sq_ref,
                         bq_ref, wo_ref, so_ref, bo_ref, cs_ref,
                         sn_ref, o_ref, kn_ref, vn_ref, *, heads,
                         kv_heads, head_dim, max_len, bits, group,
                         scale):
    s = pl.program_id(0)
    p = pos_ref[s]
    e = x_ref.shape[1]
    kv, d, g = kv_heads, head_dim, heads // kv_heads
    xv = x_ref[...]                                    # [1, E]
    # QKV projection, dequantized in VMEM
    wq = _dequant_w(wq_ref, sq_ref, bits, group)
    qkv = lax.dot_general(xv, wq, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    if bits == 8:
        qkv = qkv * jnp.transpose(sq_ref[...])
    qkv = qkv + bq_ref[...]
    qh = qkv[0, :e].reshape(heads, d)
    kh = qkv[0, e:e + kv * d].reshape(kv, d)
    vh = qkv[0, e + kv * d:e + 2 * kv * d].reshape(kv, d)
    # rope (half-split form), angles precomputed host-side per slot
    cos, sin = cs_ref[...], sn_ref[...]                # [1, d/2]
    half = d // 2

    def rot(t):
        t1, t2 = t[..., :half], t[..., half:]
        return jnp.concatenate([t1 * cos - t2 * sin,
                                t2 * cos + t1 * sin], -1)

    qh, kh = rot(qh), rot(kh)
    # attention: live cache rows [0, p) plus the current token's
    # in-register (kh, vh) at position p — the cache write happens
    # AFTER the kernel, equivalent to the dense path's write-then-read
    qg = qh.reshape(kv, g, d)
    ck = k_ref[...].reshape(max_len, kv, d).astype(jnp.float32)
    cv = v_ref[...].reshape(max_len, kv, d).astype(jnp.float32)
    s_cache = jnp.einsum("kgd,lkd->kgl", qg, ck) * scale
    live = lax.broadcasted_iota(jnp.int32, (1, 1, max_len), 2) < p
    s_cache = jnp.where(live, s_cache, -1e30)
    s_new = jnp.einsum("kgd,kd->kg", qg, kh)[..., None] * scale
    full = jnp.concatenate([s_cache, s_new], axis=-1)  # [kv, g, L+1]
    mx = jnp.max(full, axis=-1, keepdims=True)
    w = jnp.exp(full - mx)
    denom = jnp.sum(w, axis=-1, keepdims=True)
    o = jnp.einsum("kgl,lkd->kgd", w[..., :max_len], cv) \
        + w[..., max_len:] * vh[:, None, :]
    o = (o / denom).reshape(1, heads * d)
    # output projection
    wo = _dequant_w(wo_ref, so_ref, bits, group)
    out = lax.dot_general(o, wo, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    if bits == 8:
        out = out * jnp.transpose(so_ref[...])
    o_ref[...] = (out + bo_ref[...]).astype(o_ref.dtype)
    kn_ref[...] = kh.reshape(1, kv, d).astype(kn_ref.dtype)
    vn_ref[...] = vh.reshape(1, kv, d).astype(vn_ref.dtype)


def fused_decode_attention(x, pos, k_cache, v_cache, wqkv, sqkv, bqkv,
                           wo, so, bo, *, heads, kv_heads, bits=8,
                           group=None, rope=True, rope_base=10000.0,
                           scale=None, cache_dtype=None,
                           interpret=None):
    """The decode step's QKV-projection -> rope -> paged attention ->
    out-projection chain as ONE kernel dispatch per round
    (``matmul_impl="fused"``, paged path, chunk==1).

    Per slot the kernel: dequantizes the QKV weight tile in VMEM and
    projects the token, applies rotary embedding to q/k at the slot's
    position, attends over the slot's LIVE cache rows plus the
    current token's in-register k/v (so the cache scatter-write can
    stay OUTSIDE — the returned ``(k_new, v_new)`` rows are written
    after the kernel, which is read-equivalent to the dense path's
    write-then-read), and runs the dequantized output projection. The
    weight index maps ignore the slot grid index, so Mosaic keeps the
    tiles resident across slots instead of re-fetching per grid step.

    x: [S, E] current-token activations; pos: [S] int32;
    k_cache/v_cache: [S, L, KV, D] float caches (int8 KV composes
    with ``matmul_impl="pallas"`` instead — the fused path wants the
    unquantized read). ``wqkv``/``wo`` + scales/biases as in
    :func:`quant_matmul` (one ``bits`` for both). Returns
    ``(out [S, E], k_new [S, KV, D], v_new [S, KV, D])`` with k_new
    already roped. Numerics: plain (not streaming) softmax in f32
    over L+1 scores — token-stable vs the unfused path, not bitwise
    (different contraction blocking), which is why "fused" is its own
    knob value rather than an automatic upgrade of "pallas"."""
    if interpret is None:
        interpret = _use_interpret()
    _count_dispatch()
    s_, e = x.shape
    l_, kv, d = k_cache.shape[1], k_cache.shape[2], k_cache.shape[3]
    fq = wqkv.shape[0]
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    half = d // 2
    pos = jnp.asarray(pos, jnp.int32)
    if rope:
        freq = rope_base ** (-jnp.arange(half,
                                         dtype=jnp.float32) / half)
        ang = pos[:, None].astype(jnp.float32) * freq[None, :]
        cs, sn = jnp.cos(ang), jnp.sin(ang)
    else:
        # identity rotation: cos=1/sin=0 make rot() exact pass-through
        cs = jnp.ones((s_, half), jnp.float32)
        sn = jnp.zeros((s_, half), jnp.float32)
    if bits == 4:
        sq2, so2 = sqkv, so
    else:
        sq2, so2 = sqkv.reshape(fq, 1), so.reshape(e, 1)
    bq2 = bqkv.reshape(1, fq).astype(jnp.float32)
    bo2 = bo.reshape(1, e).astype(jnp.float32)
    cdt = jnp.dtype(cache_dtype) if cache_dtype else k_cache.dtype

    def full(i, pref):
        return (np.int32(0), np.int32(0))

    def slot2(i, pref):
        return (i, np.int32(0))

    def slot4(i, pref):
        return (i, np.int32(0), np.int32(0), np.int32(0))

    def slot3(i, pref):
        return (i, np.int32(0), np.int32(0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s_,),
        in_specs=[
            pl.BlockSpec((1, e), slot2),                   # x
            pl.BlockSpec((1, l_, kv, d), slot4),           # k cache
            pl.BlockSpec((1, l_, kv, d), slot4),           # v cache
            pl.BlockSpec((fq, wqkv.shape[1]), full),       # wqkv
            pl.BlockSpec((fq, sq2.shape[1]), full),        # sqkv
            pl.BlockSpec((1, fq), full),                   # bqkv
            pl.BlockSpec((e, wo.shape[1]), full),          # wo
            pl.BlockSpec((e, so2.shape[1]), full),         # so
            pl.BlockSpec((1, e), full),                    # bo
            pl.BlockSpec((1, half), slot2),                # cos
            pl.BlockSpec((1, half), slot2),                # sin
        ],
        out_specs=[
            pl.BlockSpec((1, e), slot2),
            pl.BlockSpec((1, kv, d), slot3),
            pl.BlockSpec((1, kv, d), slot3),
        ],
    )
    out, kn, vn = pl.pallas_call(
        functools.partial(_fused_decode_kernel, heads=heads,
                          kv_heads=kv, head_dim=d, max_len=l_,
                          bits=bits, group=group, scale=float(scale)),
        out_shape=[
            jax.ShapeDtypeStruct((s_, e), x.dtype),
            jax.ShapeDtypeStruct((s_, kv, d), cdt),
            jax.ShapeDtypeStruct((s_, kv, d), cdt),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(pos, x, k_cache, v_cache, wqkv, sq2, bq2, wo, so2, bo2, cs, sn)
    return out, kn, vn
