"""Learning-rate schedulers.

Parity: ``/root/reference/python/mxnet/lr_scheduler.py`` — FactorScheduler
(lr *= factor every `step` updates) and MultiFactorScheduler (explicit step
list). Schedulers are called with the global update count.
"""
from __future__ import annotations

import logging

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler"]


class LRScheduler:
    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr

    def __call__(self, num_update):
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    """lr = base_lr * factor^(floor(num_update/step))."""

    def __init__(self, step, factor=1, stop_factor_lr=1e-8):
        super().__init__()
        if step < 1:
            raise ValueError("Schedule step must be greater or equal than 1")
        if factor > 1.0:
            raise ValueError("Factor must be no more than 1 to make lr reduce")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self.count = 0

    def __call__(self, num_update):
        while num_update > self.count + self.step:
            self.count += self.step
            self.base_lr *= self.factor
            if self.base_lr < self.stop_factor_lr:
                self.base_lr = self.stop_factor_lr
                logging.info("Update[%d]: now learning rate arrived at %0.5e, "
                             "will not change in the future", num_update,
                             self.base_lr)
            else:
                logging.info("Update[%d]: Change learning rate to %0.5e",
                             num_update, self.base_lr)
        return self.base_lr


class MultiFactorScheduler(LRScheduler):
    """Reduce lr by factor at each step in an increasing step list."""

    def __init__(self, step, factor=1):
        super().__init__()
        assert isinstance(step, list) and len(step) >= 1
        for i, _step in enumerate(step):
            if i != 0 and step[i] <= step[i - 1]:
                raise ValueError("Schedule step must be an increasing list")
            if _step < 1:
                raise ValueError("Schedule step must be greater or equal than 1")
        if factor > 1.0:
            raise ValueError("Factor must be no more than 1 to make lr reduce")
        self.step = step
        self.cur_step_ind = 0
        self.factor = factor
        self.count = 0

    def __call__(self, num_update):
        while self.cur_step_ind <= len(self.step) - 1:
            if num_update > self.step[self.cur_step_ind]:
                self.count = self.step[self.cur_step_ind]
                self.cur_step_ind += 1
                self.base_lr *= self.factor
                logging.info("Update[%d]: Change learning rate to %0.5e",
                             num_update, self.base_lr)
            else:
                return self.base_lr
        return self.base_lr


class CosineScheduler(LRScheduler):
    """Cosine decay from base_lr to final_lr over max_update steps, with
    optional linear warmup (beyond the 2015 reference — the standard
    modern large-batch recipe; pairs with ParallelTrainer/bf16)."""

    def __init__(self, max_update, final_lr=0.0, warmup_steps=0,
                 warmup_begin_lr=0.0):
        super().__init__()
        if max_update < 1:
            raise ValueError("max_update must be >= 1")
        if warmup_steps >= max_update:
            raise ValueError("warmup_steps must be < max_update")
        self.max_update = max_update
        self.final_lr = final_lr
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr

    def __call__(self, num_update):
        import math
        if num_update < self.warmup_steps:
            return self.warmup_begin_lr + \
                (self.base_lr - self.warmup_begin_lr) * \
                num_update / max(self.warmup_steps, 1)
        t = min(num_update - self.warmup_steps,
                self.max_update - self.warmup_steps)
        frac = t / (self.max_update - self.warmup_steps)
        return self.final_lr + (self.base_lr - self.final_lr) * \
            0.5 * (1 + math.cos(math.pi * frac))


class PolyScheduler(LRScheduler):
    """Polynomial decay: lr = base_lr * (1 - t/max_update)^power (the
    FCN/segmentation recipe)."""

    def __init__(self, max_update, power=2.0, final_lr=0.0):
        super().__init__()
        if max_update < 1:
            raise ValueError("max_update must be >= 1")
        self.max_update = max_update
        self.power = power
        self.final_lr = final_lr

    def __call__(self, num_update):
        t = min(num_update, self.max_update)
        return self.final_lr + (self.base_lr - self.final_lr) * \
            (1.0 - t / self.max_update) ** self.power


__all__ += ["CosineScheduler", "PolyScheduler"]
