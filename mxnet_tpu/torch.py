"""Torch interop: embed torch modules/criterions as operators.

Parity: ``plugin/torch`` (torch_module-inl.h, torch_criterion-inl.h — Lua
Torch modules run as MXNet ops) and ``python/mxnet/torch.py`` (torch
function dispatch on NDArrays). The modern analogue embeds **PyTorch**
``nn.Module``s: forward/backward run on host through torch autograd,
bridged into the traced graph with ``jax.pure_callback`` (same design as
the reference's synchronous NativeOp bridge, operator.py custom ops).
CPU-torch only — this is an interop escape hatch, not the fast path.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from .ndarray import NDArray, array
from .operator import PythonOp

__all__ = ["to_torch", "from_torch", "TorchModuleOp", "th_function"]


def _torch():
    try:
        import torch
        return torch
    except ImportError as e:  # pragma: no cover
        raise MXNetError("torch is not available: %s" % e)


def to_torch(nd_arr):
    """NDArray -> torch.Tensor (host copy)."""
    torch = _torch()
    return torch.from_numpy(np.ascontiguousarray(nd_arr.asnumpy()))


def from_torch(tensor, ctx=None):
    """torch.Tensor -> NDArray."""
    return array(tensor.detach().cpu().numpy())


def th_function(fn, *nds):
    """Apply a torch function elementwise-compatibly on NDArrays
    (reference mxnet.th.* dispatch)."""
    outs = fn(*[to_torch(x) for x in nds])
    if isinstance(outs, (list, tuple)):
        return [from_torch(o) for o in outs]
    return from_torch(outs)


class TorchModuleOp(PythonOp):
    """Wrap a ``torch.nn.Module`` as a symbolic operator.

    The module's parameters are torch-owned (updated by torch optimizers if
    desired); the op exposes only data inputs, like the reference's
    TorchModule with frozen params. Gradients w.r.t. inputs flow back into
    the surrounding XLA graph.
    """

    def __init__(self, module, num_inputs=1, need_top_grad=True):
        super().__init__(need_top_grad=need_top_grad)
        self.module = module
        self.num_inputs = num_inputs
        self._saved = None

    def list_arguments(self):
        return ["data"] if self.num_inputs == 1 \
            else ["data%d" % i for i in range(self.num_inputs)]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        torch = _torch()
        with torch.no_grad():
            dummies = [torch.zeros(*s) for s in in_shape]
            out = self.module(*dummies)
        return in_shape, [list(out.shape)]

    def forward(self, in_data, out_data):
        torch = _torch()
        xs = [torch.from_numpy(np.ascontiguousarray(a)).requires_grad_(True)
              for a in in_data]
        out = self.module(*xs)
        self._saved = (xs, out)
        out_data[0][:] = out.detach().numpy()

    def backward(self, out_grad, in_data, out_data, in_grad):
        torch = _torch()
        xs, out = self._saved if self._saved else (None, None)
        if xs is None:
            # recompute (backward without forward in this process)
            xs = [torch.from_numpy(np.ascontiguousarray(a))
                  .requires_grad_(True) for a in in_data]
            out = self.module(*xs)
        g = torch.from_numpy(np.ascontiguousarray(out_grad[0])) \
            if out_grad else torch.ones_like(out)
        grads = torch.autograd.grad(out, xs, grad_outputs=g,
                                    allow_unused=True)
        for dst, gt in zip(in_grad, grads):
            dst[:] = 0 if gt is None else gt.numpy()
        self._saved = None
