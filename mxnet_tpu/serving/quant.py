"""Weight-only int8 quantization for the serving engine
(doc/serving.md "Quantized weights").

Decode is memory-bound, and at serving batch sizes the WEIGHT stream —
not the KV stream — dominates bytes per token: every matmul reads its
full weight matrix once per fused step however many slots share it.
Storing those weights int8 with per-output-channel f32 scales cuts the
stream to 1 byte/elem (the int8-KV lesson of doc/serving.md "Paged
attention", applied to the other half of the traffic).

Scheme — the same symmetric amax/127 discipline the int8 KV cache uses
(``parallel/decode.py`` ``_quantize_rows``), one scale per OUTPUT
channel:

* every quantizable weight in the LM contracts over its LAST axis
  (``qkv_weight``/``out_weight`` ``[F, E]``, FullyConnected
  ``[out, in]``, Embedding ``[vocab, E]`` rows, MoE expert stacks
  ``[X, H, E]`` / ``[X, E, H]``), so "per output channel" is uniformly
  "per all-but-last-axis row": ``scale = amax(|w|, axis=-1) / 127``,
  ``q = round(w / scale)``. One outlier row cannot poison its
  neighbours, and the scale tensor is D-fold smaller than the weight.
* LayerNorm gains, biases, and positional-embedding tables stay float
  — they are tiny, and their consumers run the generic op forwards.

Dequantization happens ON THE FLY inside the traced programs, never as
a materialized float copy of the weight (the PR 11 int8-KV lesson: the
dense int8 cache path used to dequantize the whole buffer every step).
:func:`scale_fused_matmul` applies the per-output-channel scale AFTER
the dot — ``(x @ q^T) * scale`` equals ``x @ (q * scale)^T`` exactly —
and walks the weight in output-channel CHUNKS inside one
``lax.fori_loop``, so the float staging is one chunk, not one weight:
the compiled program reads the stored int8 stream plus a bounded
scratch, which is also what keeps the XLA cost model's
``bytes_accessed`` for the decode program at the quantized width
(doc/serving.md "Measuring it"). Chunking over output channels is a
partition of independent dot products — NOT a reassociation — so the
chunked product is bitwise identical to the unchunked one, which is
what makes tp>1 quantized engines byte-identical to tp=1 quantized.

Wiring: ``Decoder(weight_dtype="int8")`` quantizes at construction
(offline generate/beam run quantized too);
``InferenceEngine(weight_dtype="int8")`` quantizes the ENGINE's own
parameter copy, leaving the decoder float so one set of weights can
serve a quantized engine next to its fp oracle (the identity tests
do). ``MXNET_SERVING_WEIGHT_DTYPE`` sets the default for both.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError

__all__ = ["QuantizedTensor", "quantize_tensor", "dequantize",
           "quantized_weight_names", "quantize_params",
           "scale_fused_matmul"]

# op name -> input indices that are quantizable matmul weights (the
# consumers Decoder._run / _cached_mha intercept); every OTHER consumer
# position vetoes quantization of its variable, so a name is quantized
# only when every consumer dequantizes it on the fly
_QUANT_ARGS = {
    "FullyConnected": (1,),
    "Embedding": (1,),
    "MultiHeadAttention": (1, 3),          # qkv_weight, out_weight
    "MoEFFN": (1, 2, 4),                   # gate, expert_w1, expert_w2
}


class QuantizedTensor:
    """An int8 weight with per-output-channel f32 scales.

    ``q``: int8, the original weight's shape. ``scale``: f32,
    ``q.shape[:-1]`` (one per all-but-last-axis row — the output
    channel under the LM's uniform ``[out..., contract]`` weight
    layouts). ``dtype``: the dequantization target (the dtype the
    float weight had — ``compute_dtype`` under a casting decoder).

    Registered as a jax pytree, so parameter dicts containing
    quantized entries flow through ``jit`` / ``device_put`` /
    ``shard_map`` untouched; the consuming ops dispatch on
    ``isinstance`` at trace time.
    """

    __slots__ = ("q", "scale", "dtype")

    def __init__(self, q, scale, dtype):
        self.q = q
        self.scale = scale
        self.dtype = dtype

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self):
        return self.q.nbytes + self.scale.nbytes

    def __repr__(self):
        return ("QuantizedTensor(shape=%r, dtype=%r)"
                % (tuple(self.q.shape), self.dtype))


jax.tree_util.register_pytree_node(
    QuantizedTensor,
    lambda t: ((t.q, t.scale), t.dtype),
    lambda dtype, ch: QuantizedTensor(ch[0], ch[1], dtype))


def quantize_tensor(w, dtype=None):
    """Quantize one float weight to :class:`QuantizedTensor`:
    symmetric per-output-channel ``amax/127`` (all-zero rows get scale
    1 so dequantization is exact zero). ``dtype`` is the dequant
    target (default: ``w``'s own dtype)."""
    w = jnp.asarray(w)
    if w.ndim < 2:
        raise MXNetError(
            "quantize_tensor: per-output-channel quantization needs a "
            "rank >= 2 weight, got shape %r" % (tuple(w.shape),))
    if dtype is None:
        dtype = str(w.dtype)
    wf = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(wf), axis=-1) / 127.0
    s = jnp.where(s > 0, s, 1.0).astype(jnp.float32)
    q = jnp.round(wf / s[..., None]).astype(jnp.int8)
    return QuantizedTensor(q, s, str(jnp.dtype(dtype)))


def dequantize(qt):
    """The float weight a :class:`QuantizedTensor` stands for —
    testing/debugging only: the serving programs never materialize
    this (see :func:`scale_fused_matmul`)."""
    return (qt.q.astype(jnp.float32)
            * qt.scale[..., None]).astype(qt.dtype)


def quantized_weight_names(topo):
    """Which parameter names of a Decoder's topological node walk are
    safely quantizable: variables consumed ONLY at the matmul-weight
    positions of the intercepted ops (attention QKV/out projections,
    FullyConnected weights — the MLP and the unembedding head —
    Embedding tables, MoE gate/expert stacks). A name any other
    consumer touches (data, biases, LayerNorm gains, positional
    tables, or an op the quantized forwards do not cover) is left
    float."""
    want, veto = set(), set()
    for n in topo:
        if n.is_var:
            continue
        idxs = _QUANT_ARGS.get(n.spec.name, ())
        for j, (inp, _) in enumerate(n.inputs):
            if not inp.is_var:
                continue
            (want if j in idxs else veto).add(inp.name)
    return want - veto


def quantize_params(params, names):
    """Quantize ``names`` of a parameter dict (each entry keeps its
    own dtype as the dequant target); everything else passes through
    by reference."""
    return {k: quantize_tensor(v, dtype=str(jnp.asarray(v).dtype))
            if k in names else v
            for k, v in params.items()}


def _block_rows(f):
    """Output-channel chunk height for the fused-dequant loop: the
    largest of (256 .. 8) dividing ``f`` into at least 8 chunks —
    the float staging (convert + dot read of ONE chunk) must be a
    small fraction of the int8 stream for the loop to pay, in the
    cost model and in scratch bytes alike — falling back to >= 2
    chunks for small weights, else None (tiny weights dequantize
    whole: same math, the loop would buy nothing)."""
    for least in (8, 2):
        for r in (256, 128, 64, 32, 16, 8):
            if f % r == 0 and f // r >= least:
                return r
    return None


def scale_fused_matmul(x, qt):
    """``x [..., E] @ qt [F, E]^T`` with the per-output-channel scale
    applied to the product: returns ``[..., F]`` in ``x``'s dtype.

    The scale multiplies the OUTPUT (``(x @ q^T) * s == x @ (q*s)^T``
    exactly), so the int8 weight feeds the dot directly and no float
    copy of the weight ever exists. The weight is walked in
    output-channel chunks inside one ``lax.fori_loop``: each chunk is
    dequantization-staged at chunk size (a bounded scratch, the
    kernel-VMEM analogue) and its product written into the output
    slice. Chunking partitions independent output channels — bitwise
    identical to the unchunked product, at any chunk count."""
    q, s = qt.q, qt.scale
    f = q.shape[0]

    def piece(wc, sc):
        oc = jnp.einsum("...e,fe->...f", x, wc.astype(x.dtype))
        return oc * sc.astype(x.dtype)

    r = _block_rows(f)
    if r is None:
        return piece(q, s)
    out0 = jnp.zeros(x.shape[:-1] + (f,), x.dtype)
    ax = out0.ndim - 1

    def body(i, out):
        wc = lax.dynamic_slice_in_dim(q, i * r, r, axis=0)
        sc = lax.dynamic_slice_in_dim(s, i * r, r, axis=0)
        return lax.dynamic_update_slice_in_dim(out, piece(wc, sc),
                                               i * r, axis=ax)

    return lax.fori_loop(0, f // r, body, out0)


def embedding_rows(qt, idx):
    """Quantized Embedding lookup: gather int8 rows and their scales,
    dequantize only the GATHERED rows — the table itself is read at
    1 byte/elem (per-row scales are per-output-channel here: the
    vocab row IS the output channel)."""
    rows = jnp.take(qt.q, idx, axis=0).astype(jnp.float32)
    sc = jnp.take(qt.scale, idx, axis=0)
    return (rows * sc[..., None]).astype(qt.dtype)


def _expert_matmul(h, qt):
    """``h [B, T, X, H] x w2 [X, E, H] -> [B, T, X, E]`` (the MoE
    down-projection, contraction per expert) with on-the-fly dequant:
    a ``fori_loop`` over experts, each expert's slice staged at expert
    size. Bitwise identical to the unchunked einsum on the
    dequantized stack (experts are independent output blocks)."""
    q, s = qt.q, qt.scale
    nx = q.shape[0]
    out0 = jnp.zeros(h.shape[:2] + (nx, q.shape[1]), h.dtype)

    def body(i, out):
        qc = lax.dynamic_slice_in_dim(q, i, 1, axis=0)
        sc = lax.dynamic_slice_in_dim(s, i, 1, axis=0)
        hc = lax.dynamic_slice_in_dim(h, i, 1, axis=2)
        oc = jnp.einsum("btxh,xeh->btxe", hc, qc.astype(h.dtype)) \
            * sc.astype(h.dtype)[None, None]
        return lax.dynamic_update_slice_in_dim(out, oc, i, axis=2)

    return lax.fori_loop(0, nx, body, out0)


def moe_ffn_forward(p, ins):
    """MoEFFN forward with any mix of quantized/float weights: the
    routing + combine math is ``ops.attention.moe_ffn_math`` — the
    SAME implementation the fp op runs — with the matmul of each
    quantized weight swapped for its scale-fused form."""
    from ..ops.attention import moe_ffn_math

    def gate_mm(x, w):
        if isinstance(w, QuantizedTensor):
            return scale_fused_matmul(x, w)
        return jnp.einsum("bte,xe->btx", x, w)

    def up_mm(x, w):
        if not isinstance(w, QuantizedTensor):
            return jnp.einsum("bte,xhe->btxh", x, w)
        # [X, H, E] contracts E with output channels (x, h): the 2-D
        # chunked helper over the flattened [X*H, E] view is the same
        # einsum, bitwise
        xq, hq, e = w.q.shape
        flat = QuantizedTensor(w.q.reshape(xq * hq, e),
                               w.scale.reshape(xq * hq), w.dtype)
        return scale_fused_matmul(x, flat).reshape(
            x.shape[:-1] + (xq, hq))

    def down_mm(h, w):
        if isinstance(w, QuantizedTensor):
            return _expert_matmul(h, w)
        return jnp.einsum("btxh,xeh->btxe", h, w)

    return moe_ffn_math(p, ins, gate_mm=gate_mm, up_mm=up_mm,
                        down_mm=down_mm)


def weight_nbytes(params):
    """Total stored bytes of a parameter dict (quantized entries count
    int8 values + scales) — the ``serving.weight_bytes`` gauge."""
    return int(sum(leaf.nbytes
                   for leaf in jax.tree_util.tree_leaves(params)))
