"""Weight-only int8 quantization for the serving engine
(doc/serving.md "Quantized weights").

Decode is memory-bound, and at serving batch sizes the WEIGHT stream —
not the KV stream — dominates bytes per token: every matmul reads its
full weight matrix once per fused step however many slots share it.
Storing those weights int8 with per-output-channel f32 scales cuts the
stream to 1 byte/elem (the int8-KV lesson of doc/serving.md "Paged
attention", applied to the other half of the traffic).

Scheme — the same symmetric amax/127 discipline the int8 KV cache uses
(``parallel/decode.py`` ``_quantize_rows``), one scale per OUTPUT
channel:

* every quantizable weight in the LM contracts over its LAST axis
  (``qkv_weight``/``out_weight`` ``[F, E]``, FullyConnected
  ``[out, in]``, Embedding ``[vocab, E]`` rows, MoE expert stacks
  ``[X, H, E]`` / ``[X, E, H]``), so "per output channel" is uniformly
  "per all-but-last-axis row": ``scale = amax(|w|, axis=-1) / 127``,
  ``q = round(w / scale)``. One outlier row cannot poison its
  neighbours, and the scale tensor is D-fold smaller than the weight.
* LayerNorm gains, biases, and positional-embedding tables stay float
  — they are tiny, and their consumers run the generic op forwards.

Dequantization happens ON THE FLY inside the traced programs, never as
a materialized float copy of the weight (the PR 11 int8-KV lesson: the
dense int8 cache path used to dequantize the whole buffer every step).
:func:`scale_fused_matmul` applies the per-output-channel scale AFTER
the dot — ``(x @ q^T) * scale`` equals ``x @ (q * scale)^T`` exactly —
and walks the weight in output-channel CHUNKS inside one
``lax.fori_loop``, so the float staging is one chunk, not one weight:
the compiled program reads the stored int8 stream plus a bounded
scratch, which is also what keeps the XLA cost model's
``bytes_accessed`` for the decode program at the quantized width
(doc/serving.md "Measuring it"). Chunking over output channels is a
partition of independent dot products — NOT a reassociation — so the
chunked product is bitwise identical to the unchunked one, which is
what makes tp>1 quantized engines byte-identical to tp=1 quantized.

Wiring: ``Decoder(weight_dtype="int8")`` quantizes at construction
(offline generate/beam run quantized too);
``InferenceEngine(weight_dtype="int8")`` quantizes the ENGINE's own
parameter copy, leaving the decoder float so one set of weights can
serve a quantized engine next to its fp oracle (the identity tests
do). ``MXNET_SERVING_WEIGHT_DTYPE`` sets the default for both.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError

__all__ = ["QuantizedTensor", "quantize_tensor", "dequantize",
           "quantized_weight_names", "quantize_params",
           "scale_fused_matmul", "pack_int4", "unpack_int4",
           "resolve_chunk", "resolve_group"]

# op name -> input indices that are quantizable matmul weights (the
# consumers Decoder._run / _cached_mha intercept); every OTHER consumer
# position vetoes quantization of its variable, so a name is quantized
# only when every consumer dequantizes it on the fly
_QUANT_ARGS = {
    "FullyConnected": (1,),
    "Embedding": (1,),
    "MultiHeadAttention": (1, 3),          # qkv_weight, out_weight
    "MoEFFN": (1, 2, 4),                   # gate, expert_w1, expert_w2
}


class QuantizedTensor:
    """A quantized weight with f32 scales, in one of two layouts.

    ``bits=8`` (the PR 15 scheme): ``q`` is int8 in the original
    weight's shape, ``scale`` is f32 of shape ``q.shape[:-1]`` (one per
    all-but-last-axis row — the output channel under the LM's uniform
    ``[out..., contract]`` weight layouts).

    ``bits=4`` (per-group, ISSUE 17): ``q`` is uint8 holding TWO
    4-bit values per byte packed along the contraction (last) axis —
    shape ``[..., E//2]`` for a float weight ``[..., E]`` — and
    ``scale`` is f32 of shape ``[..., E//group]``: one scale per
    ``group`` consecutive contraction elements of each output row.
    Group scales sit on the CONTRACTION axis, so (unlike the per-row
    int8 scale) they cannot be folded into the product after the dot —
    consumers dequantize the weight block (unpack + scale) before
    contracting, which is exactly what the Pallas ``quant_matmul``
    kernel does per VMEM tile.

    ``dtype``: the dequantization target (the dtype the float weight
    had — ``compute_dtype`` under a casting decoder).

    Registered as a jax pytree, so parameter dicts containing
    quantized entries flow through ``jit`` / ``device_put`` /
    ``shard_map`` untouched; the consuming ops dispatch on
    ``isinstance`` at trace time.
    """

    __slots__ = ("q", "scale", "dtype", "bits", "group")

    def __init__(self, q, scale, dtype, bits=8, group=None):
        self.q = q
        self.scale = scale
        self.dtype = dtype
        self.bits = bits
        self.group = group

    @property
    def shape(self):
        if self.bits == 4:
            return self.q.shape[:-1] + (2 * self.q.shape[-1],)
        return self.q.shape

    @property
    def nbytes(self):
        return self.q.nbytes + self.scale.nbytes

    def __repr__(self):
        return ("QuantizedTensor(shape=%r, dtype=%r, bits=%d%s)"
                % (tuple(self.shape), self.dtype, self.bits,
                   "" if self.group is None
                   else ", group=%d" % self.group))


jax.tree_util.register_pytree_node(
    QuantizedTensor,
    lambda t: ((t.q, t.scale), (t.dtype, t.bits, t.group)),
    lambda aux, ch: QuantizedTensor(ch[0], ch[1], *aux))


def pack_int4(q):
    """Pack an int array of 4-bit values (range [-8, 7]) pairwise
    along the last axis into uint8: byte ``i`` holds element ``2i`` in
    its low nibble and ``2i+1`` in its high nibble. The last axis must
    be even. Exact inverse of :func:`unpack_int4` (bitwise)."""
    q = jnp.asarray(q)
    lo = (q[..., 0::2] & 0xF).astype(jnp.uint8)
    hi = (q[..., 1::2] & 0xF).astype(jnp.uint8)
    return lo | (hi << 4)


def unpack_int4(u, dtype=jnp.int8):
    """Unpack :func:`pack_int4` bytes back to signed 4-bit values
    ``[..., 2*E2]`` (sign-extended two's complement nibbles)."""
    u = jnp.asarray(u)
    lo = (u & 0xF).astype(jnp.int32)
    hi = ((u >> 4) & 0xF).astype(jnp.int32)
    both = jnp.stack([lo, hi], axis=-1).reshape(u.shape[:-1]
                                                + (2 * u.shape[-1],))
    return (both - 16 * (both >= 8)).astype(dtype)


def resolve_group(n, group=None):
    """The per-group scale width for a contraction axis of size ``n``
    under int4 quantization. ``group=None`` reads ``MXNET_QUANT_GROUP``
    (unset = auto). Auto picks the largest of (128, 64, 32, 16, 8, 4,
    2) dividing ``n``; an explicit group must be an even divisor of
    ``n`` or the whole axis is refused loudly — silent shrinking would
    quietly change the recorded bytes ratio."""
    if group is None:
        env = os.environ.get("MXNET_QUANT_GROUP", "").strip()
        group = int(env) if env else None
    if group is None:
        for g in (128, 64, 32, 16, 8, 4, 2):
            if n % g == 0:
                return g
        raise MXNetError(
            "int4 quantization needs an even contraction axis to pack "
            "nibble pairs, got axis size %d" % n)
    group = int(group)
    if group <= 0 or group % 2 or n % group:
        raise MXNetError(
            "MXNET_QUANT_GROUP=%d must be a positive even divisor of "
            "the contraction axis (%d here); pick a divisor or unset "
            "it for the auto choice" % (group, n))
    return group


def quantize_tensor(w, dtype=None, bits=8, group=None):
    """Quantize one float weight to :class:`QuantizedTensor`.

    ``bits=8``: symmetric per-output-channel ``amax/127`` (all-zero
    rows get scale 1 so dequantization is exact zero). ``bits=4``:
    symmetric per-group ``amax/7`` over ``group`` consecutive
    contraction elements (see :func:`resolve_group`), values packed
    two per byte. ``dtype`` is the dequant target (default: ``w``'s
    own dtype)."""
    w = jnp.asarray(w)
    if w.ndim < 2:
        raise MXNetError(
            "quantize_tensor: per-output-channel quantization needs a "
            "rank >= 2 weight, got shape %r" % (tuple(w.shape),))
    if dtype is None:
        dtype = str(w.dtype)
    dtype = str(jnp.dtype(dtype))
    wf = w.astype(jnp.float32)
    if bits == 8:
        s = jnp.max(jnp.abs(wf), axis=-1) / 127.0
        s = jnp.where(s > 0, s, 1.0).astype(jnp.float32)
        q = jnp.round(wf / s[..., None]).astype(jnp.int8)
        return QuantizedTensor(q, s, dtype)
    if bits != 4:
        raise MXNetError("quantize_tensor: bits must be 8 or 4, got %r"
                         % (bits,))
    e = w.shape[-1]
    g = resolve_group(e, group)
    wg = wf.reshape(wf.shape[:-1] + (e // g, g))
    s = jnp.max(jnp.abs(wg), axis=-1) / 7.0
    s = jnp.where(s > 0, s, 1.0).astype(jnp.float32)
    q4 = jnp.round(wg / s[..., None]).astype(jnp.int32)
    q4 = q4.reshape(wf.shape)
    return QuantizedTensor(pack_int4(q4), s, dtype, bits=4, group=g)


def _group_scales(qt, scale_slice=None):
    """Expand a per-group scale block to per-element width along the
    contraction axis (``[..., E//g] -> [..., E]``)."""
    s = qt.scale if scale_slice is None else scale_slice
    return jnp.repeat(s, qt.group, axis=-1)


def dequantize(qt):
    """The float weight a :class:`QuantizedTensor` stands for —
    testing/debugging only: the serving programs never materialize
    this (see :func:`scale_fused_matmul`)."""
    if qt.bits == 4:
        v = unpack_int4(qt.q, dtype=jnp.float32)
        return (v * _group_scales(qt)).astype(qt.dtype)
    return (qt.q.astype(jnp.float32)
            * qt.scale[..., None]).astype(qt.dtype)


def quantized_weight_names(topo):
    """Which parameter names of a Decoder's topological node walk are
    safely quantizable: variables consumed ONLY at the matmul-weight
    positions of the intercepted ops (attention QKV/out projections,
    FullyConnected weights — the MLP and the unembedding head —
    Embedding tables, MoE gate/expert stacks). A name any other
    consumer touches (data, biases, LayerNorm gains, positional
    tables, or an op the quantized forwards do not cover) is left
    float."""
    want, veto = set(), set()
    for n in topo:
        if n.is_var:
            continue
        idxs = _QUANT_ARGS.get(n.spec.name, ())
        for j, (inp, _) in enumerate(n.inputs):
            if not inp.is_var:
                continue
            (want if j in idxs else veto).add(inp.name)
    return want - veto


def quantize_params(params, names, bits=8, group=None, row_quant=()):
    """Quantize ``names`` of a parameter dict (each entry keeps its
    own dtype as the dequant target); everything else passes through
    by reference. ``bits``/``group`` select the scheme; names in
    ``row_quant`` (Embedding tables, whose consumer gathers whole
    rows host-side) stay per-row int8 even under ``bits=4`` — packed
    nibbles cannot be row-gathered cheaply and the tables are a small
    slice of the stream."""
    def one(k, v):
        if k not in names:
            return v
        b = 8 if k in row_quant else bits
        return quantize_tensor(v, dtype=str(jnp.asarray(v).dtype),
                               bits=b, group=group)
    return {k: one(k, v) for k, v in params.items()}


def _block_rows(f):
    """Default output-channel chunk height for the fused-dequant loop:
    the largest of (256 .. 8) dividing ``f`` into at least 8 chunks —
    the float staging (convert + dot read of ONE chunk) must be a
    small fraction of the int8 stream for the loop to pay, in the
    cost model and in scratch bytes alike — falling back to >= 2
    chunks for small weights, else None (tiny weights dequantize
    whole: same math, the loop would buy nothing)."""
    for least in (8, 2):
        for r in (256, 128, 64, 32, 16, 8):
            if f % r == 0 and f // r >= least:
                return r
    return None


def resolve_chunk(f):
    """Output-channel chunk for a weight with ``f`` output rows.
    ``MXNET_QUANT_CHUNK`` overrides the :func:`_block_rows` divisor
    table explicitly; a non-divisor value is refused with a loud
    ``MXNetError`` instead of silently falling back (the silent pick
    made the staging footprint — and the cost model's read of it —
    depend on a hidden table). ``0``/unset = the auto pick. A chunk
    >= ``f`` means "dequantize whole" (returned as None, like the
    auto path's tiny-weight fallback)."""
    env = os.environ.get("MXNET_QUANT_CHUNK", "").strip()
    if not env or env == "0":
        return _block_rows(f)
    try:
        r = int(env)
    except ValueError:
        raise MXNetError(
            "MXNET_QUANT_CHUNK=%r is not an integer chunk size" % env)
    if r < 0 or (r < f and f % r):
        raise MXNetError(
            "MXNET_QUANT_CHUNK=%d must divide the weight's output-"
            "channel count (%d here): the chunk walk partitions "
            "output rows exactly; pick a divisor or 0 for the auto "
            "choice" % (r, f))
    return None if r >= f else r


def _dequant_rows(qt, wc, sc, dtype):
    """Dequantize one output-row chunk ``wc`` (with its scale slice
    ``sc``) to ``dtype``. int8: values scaled per row AFTER this via
    the caller (returns the raw cast); int4: unpack + per-group scale
    on the contraction axis (must happen before the dot)."""
    if qt.bits == 4:
        v = unpack_int4(wc, dtype=jnp.float32)
        return (v * jnp.repeat(sc, qt.group, axis=-1)).astype(dtype)
    return wc.astype(dtype)


def scale_fused_matmul(x, qt):
    """``x [..., E] @ qt [F, E]^T`` with on-the-fly dequantization:
    returns ``[..., F]`` in ``x``'s dtype.

    int8: the per-output-channel scale multiplies the OUTPUT
    (``(x @ q^T) * s == x @ (q*s)^T`` exactly), so the int8 weight
    feeds the dot directly and no float copy of the weight ever
    exists. int4: per-group scales sit on the contraction axis, so
    each chunk is unpacked and scaled BEFORE its dot — still only one
    chunk of float staging. Either way the weight is walked in
    output-channel chunks inside one ``lax.fori_loop``
    (:func:`resolve_chunk` — ``MXNET_QUANT_CHUNK``): chunking
    partitions independent output channels — bitwise identical to the
    unchunked product, at any chunk count."""
    q, s = qt.q, qt.scale
    f = q.shape[0]

    def piece(wc, sc):
        if qt.bits == 4:
            w = _dequant_rows(qt, wc, sc, x.dtype)
            return jnp.einsum("...e,fe->...f", x, w)
        oc = jnp.einsum("...e,fe->...f", x, wc.astype(x.dtype))
        return oc * sc.astype(x.dtype)

    r = resolve_chunk(f)
    if r is None:
        return piece(q, s)
    out0 = jnp.zeros(x.shape[:-1] + (f,), x.dtype)
    ax = out0.ndim - 1

    def body(i, out):
        wc = lax.dynamic_slice_in_dim(q, i * r, r, axis=0)
        sc = lax.dynamic_slice_in_dim(s, i * r, r, axis=0)
        return lax.dynamic_update_slice_in_dim(out, piece(wc, sc),
                                               i * r, axis=ax)

    return lax.fori_loop(0, f // r, body, out0)


def embedding_rows(qt, idx):
    """Quantized Embedding lookup: gather int8 rows and their scales,
    dequantize only the GATHERED rows — the table itself is read at
    1 byte/elem (per-row scales are per-output-channel here: the
    vocab row IS the output channel). Embedding tables are always
    per-row int8 (``quantize_params(row_quant=...)``): a packed-nibble
    row gather would read-modify every byte for half its bits."""
    rows = jnp.take(qt.q, idx, axis=0).astype(jnp.float32)
    sc = jnp.take(qt.scale, idx, axis=0)
    return (rows * sc[..., None]).astype(qt.dtype)


def expert_slice(qt, i):
    """Static expert ``i`` of a stacked MoE :class:`QuantizedTensor`
    (``[X, out, contract]`` values + matching scales) as its own 2-D
    quantized weight — what the per-expert Pallas matmul dispatches
    on."""
    return QuantizedTensor(qt.q[i], qt.scale[i], qt.dtype,
                           bits=qt.bits, group=qt.group)


def _expert_matmul(h, qt):
    """``h [B, T, X, H] x w2 [X, E, H] -> [B, T, X, E]`` (the MoE
    down-projection, contraction per expert) with on-the-fly dequant:
    a ``fori_loop`` over experts, each expert's slice staged at expert
    size. Bitwise identical to the unchunked einsum on the
    dequantized stack (experts are independent output blocks)."""
    q, s = qt.q, qt.scale
    nx = q.shape[0]
    out0 = jnp.zeros(h.shape[:2] + (nx, q.shape[1]), h.dtype)

    def body(i, out):
        qc = lax.dynamic_slice_in_dim(q, i, 1, axis=0)
        sc = lax.dynamic_slice_in_dim(s, i, 1, axis=0)
        hc = lax.dynamic_slice_in_dim(h, i, 1, axis=2)
        if qt.bits == 4:
            w = _dequant_rows(qt, qc, sc, h.dtype)
            oc = jnp.einsum("btxh,xeh->btxe", hc, w)
        else:
            oc = jnp.einsum("btxh,xeh->btxe", hc, qc.astype(h.dtype)) \
                * sc.astype(h.dtype)[None, None]
        return lax.dynamic_update_slice_in_dim(out, oc, i, axis=2)

    return lax.fori_loop(0, nx, body, out0)


def moe_ffn_forward(p, ins, mm=None, ep=None):
    """MoEFFN forward with any mix of quantized/float weights: the
    routing + combine math is ``ops.attention.moe_ffn_math`` — the
    SAME implementation the fp op runs — with the matmul of each
    quantized weight swapped for its scale-fused form.

    ``mm`` (optional) replaces :func:`scale_fused_matmul` for the 2-D
    quantized products — the ``matmul_impl="pallas"`` hook: the MoE
    expert stack rides the SAME kernel as the dense projections
    through these pluggable matmuls. ``ep=(axis_name, degree)`` runs
    the math expert-parallel: the stacks arrive sharded on the expert
    axis and ``moe_ffn_math`` gathers gate logits / psums the combine
    (doc/serving.md "Expert-parallel MoE")."""
    from ..ops.attention import moe_ffn_math
    qmm = mm if mm is not None else scale_fused_matmul

    def gate_mm(x, w):
        if isinstance(w, QuantizedTensor):
            return qmm(x, w)
        return jnp.einsum("bte,xe->btx", x, w)

    def up_mm(x, w):
        if not isinstance(w, QuantizedTensor):
            return jnp.einsum("bte,xhe->btxh", x, w)
        # [X, H, E] contracts E with output channels (x, h): the 2-D
        # helper over the flattened [X*H, E] view is the same einsum,
        # bitwise
        xq, hq = w.q.shape[:2]
        flat = QuantizedTensor(
            w.q.reshape((xq * hq,) + w.q.shape[2:]),
            w.scale.reshape((xq * hq,) + w.scale.shape[2:]),
            w.dtype, bits=w.bits, group=w.group)
        return qmm(x, flat).reshape(x.shape[:-1] + (xq, hq))

    def down_mm(h, w):
        if not isinstance(w, QuantizedTensor):
            return jnp.einsum("btxh,xeh->btxe", h, w)
        if mm is None:
            return _expert_matmul(h, w)
        # kernel path: one quant_matmul per expert (trace-time unroll
        # — the expert count is static and, under ep, already local)
        nx = w.q.shape[0]
        cols = [mm(h[:, :, i], expert_slice(w, i)) for i in range(nx)]
        return jnp.stack(cols, axis=2)

    return moe_ffn_math(p, ins, gate_mm=gate_mm, up_mm=up_mm,
                        down_mm=down_mm, ep=ep)


def weight_nbytes(params):
    """Total stored bytes of a parameter dict (quantized entries count
    int8 values + scales) — the ``serving.weight_bytes`` gauge."""
    return int(sum(leaf.nbytes
                   for leaf in jax.tree_util.tree_leaves(params)))
