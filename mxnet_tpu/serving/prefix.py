"""Host-side prefix cache bookkeeping for the serving engine.

The device side of prefix reuse is one compiled slot-to-slot row copy
per bucket (``Decoder.slot_prefix_rows`` / ``slot_write_prefix_rows``);
everything POLICY lives here, as plain python the tier-1 suite can unit
test without a single compile:

* a **trie over token ids** maps a new prompt to the longest prefix
  some retained entry shares with it (every node on an entry's path
  carries the entry, so the deepest reachable node IS the longest
  match);
* each entry owns one **pool slot** — a reserved row-region of the
  engine's device cache holding the K/V of the entry's prompt — and
  the pool is bounded by a **byte budget** (``slot_bytes`` per entry,
  ``capacity`` slots total);
* eviction is **LRU over unpinned entries**: an entry is pinned
  (``refs > 0``) while a request that matched it is still mid-prefill,
  so the bookkeeping stays valid even if copy dispatch were ever
  deferred past an insertion that wants the slot. All-pinned means
  ``insert`` declines (returns None) rather than evicting a source
  someone still schedules against.

The cache stores PROMPT prefixes only (generated tokens never enter
the trie): prompt K/V rows are a pure function of the token ids, which
is what makes a cross-request copy exact. doc/serving.md has the
determinism argument end to end.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["PrefixCache"]


class _Node:
    """One trie node: children by token id, plus every entry whose
    token path passes through this node (so any reachable node has a
    non-empty entry set — emptied subtrees are pruned on eviction)."""

    __slots__ = ("children", "entries")

    def __init__(self):
        self.children = {}
        self.entries = set()


class _Entry:
    __slots__ = ("tokens", "slot", "refs", "tick")

    def __init__(self, tokens, slot, tick):
        self.tokens = tokens        # tuple of python ints
        self.slot = slot            # pool slot index owning the rows
        self.refs = 0               # pin count (mid-prefill consumers)
        self.tick = tick            # LRU clock (bumped on every use)

    def __repr__(self):
        return ("_Entry(len=%d, slot=%d, refs=%d)"
                % (len(self.tokens), self.slot, self.refs))


class PrefixCache:
    """Refcounted-LRU prefix trie over ``capacity`` pool slots.

    ``slot_bytes`` is what one retained entry costs on device (one
    full cache slot — the engine computes it from its cache tree);
    ``bytes_used`` reports the resident total for the telemetry gauge.
    """

    def __init__(self, capacity, slot_bytes):
        capacity = int(capacity)
        if capacity < 1:
            raise MXNetError("PrefixCache: capacity must be >= 1 "
                             "(got %d); disable the cache instead"
                             % capacity)
        self.capacity = capacity
        self.slot_bytes = int(slot_bytes)
        self._root = _Node()
        self._by_tokens = {}                  # tokens tuple -> _Entry
        self._free = list(range(capacity - 1, -1, -1))  # pop() -> 0,1,..
        self._tick = 0
        self.evictions = 0
        self.inserts = 0
        self.insert_skipped = 0

    # -- introspection ---------------------------------------------------
    def __len__(self):
        return len(self._by_tokens)

    @property
    def bytes_used(self):
        return len(self._by_tokens) * self.slot_bytes

    @property
    def pinned(self):
        """Total outstanding pins (sum of entry refcounts). Every
        engine error/cancel/deadline path must return this to its
        pre-request value — a leaked pin is a pool slot that can never
        be evicted again (eventual pool starvation); the fault tests
        assert it drains back to zero."""
        return sum(e.refs for e in self._by_tokens.values())

    def entries(self):
        """Snapshot of retained entries (tests/debugging)."""
        return list(self._by_tokens.values())

    def get(self, tokens):
        """The entry retaining exactly ``tokens``, or None (no LRU
        touch — this is an existence probe, not a use)."""
        return self._by_tokens.get(tuple(int(t) for t in tokens))

    def peek(self, tokens):
        """Longest retained prefix length of ``tokens`` WITHOUT an LRU
        touch or a pin — a placement probe, not a use. The fleet
        router reads this off candidate replicas (submit affinity, and
        the decode-side handoff check where a full-prefill hit means
        no KV bytes need to ship at all); the engine re-walks with
        :meth:`lookup` at admission and takes the hit itself."""
        node, depth = self._root, 0
        for t in tokens:
            child = node.children.get(int(t))
            if child is None:
                break
            node, depth = child, depth + 1
        return depth

    # -- lookup ----------------------------------------------------------
    def lookup(self, tokens):
        """Longest cached prefix of ``tokens``: returns
        ``(matched_len, entry)`` — the deepest trie node reachable and
        the most-recently-used entry passing through it — or
        ``(0, None)`` on a miss. Touches the matched entry's LRU
        clock. The caller decides how much of the match to USE (the
        engine clips to ``len(prompt) - 1`` so a full hit still
        prefills one real token for its logits) and must
        ``acquire``/``release`` around the time the entry's rows are
        scheduled against."""
        node, depth = self._root, 0
        for t in tokens:
            child = node.children.get(int(t))
            if child is None:
                break
            node, depth = child, depth + 1
        if depth == 0:
            return 0, None
        entry = max(node.entries, key=lambda e: e.tick)
        self._tick += 1
        entry.tick = self._tick
        return depth, entry

    # -- pinning ---------------------------------------------------------
    def acquire(self, entry):
        entry.refs += 1

    def release(self, entry):
        if entry.refs <= 0:
            raise MXNetError("PrefixCache: release without acquire on "
                             "%r" % (entry,))
        entry.refs -= 1

    # -- insert / evict --------------------------------------------------
    def insert(self, tokens):
        """Retain ``tokens``'s K/V (the caller copies the rows into
        ``entry.slot`` after this returns): allocates a pool slot,
        evicting the least-recently-used UNPINNED entry if the pool is
        full. Returns the new entry, the existing one when ``tokens``
        is already retained verbatim (LRU-touched, no copy needed), or
        None when every slot is pinned (the caller skips retention)."""
        tokens = tuple(int(t) for t in tokens)
        if not tokens:
            raise MXNetError("PrefixCache: cannot retain an empty "
                             "prefix")
        hit = self._by_tokens.get(tokens)
        if hit is not None:
            self._tick += 1
            hit.tick = self._tick
            return hit
        if not self._free and not self._evict_one():
            self.insert_skipped += 1
            return None
        slot = self._free.pop()
        self._tick += 1
        entry = _Entry(tokens, slot, self._tick)
        node = self._root
        node.entries.add(entry)
        for t in tokens:
            node = node.children.setdefault(t, _Node())
            node.entries.add(entry)
        self._by_tokens[tokens] = entry
        self.inserts += 1
        return entry

    def discard(self, entry):
        """Drop a retained entry whose device rows never materialized
        (a failed retention copy): without this, a later hit would
        serve garbage rows. No-op if the entry is already gone."""
        if self._by_tokens.get(entry.tokens) is entry:
            self._remove(entry)

    def _evict_one(self):
        victim = None
        for e in self._by_tokens.values():
            if e.refs == 0 and (victim is None or e.tick < victim.tick):
                victim = e
        if victim is None:
            return False
        self._remove(victim)
        self.evictions += 1
        return True

    def _remove(self, entry):
        del self._by_tokens[entry.tokens]
        self._free.append(entry.slot)
        # unlink along the path; prune the shallowest emptied subtree
        # (removing this entry empties a node iff it empties the whole
        # subtree below it — entries live on every node of their path)
        node = self._root
        node.entries.discard(entry)
        for t in entry.tokens:
            child = node.children[t]
            child.entries.discard(entry)
            if not child.entries:
                del node.children[t]
                break
            node = child
