"""Per-request flight recorder for the serving engine.

The telemetry histograms answer "how is the fleet doing"; they cannot
answer "what happened to request 1173" once it retired — a deadline
miss or a ``retire_reason="error"`` used to leave nothing but a
counter increment behind. The :class:`FlightRecorder` keeps a bounded
ring of structured lifecycle timelines: every event the scheduler
already knows about (submit → staged → prefix hit/miss → admitted →
each prefill chunk → first token → sampled decode progress → retire
with the reason) is appended to the request's record, and the last
``retain`` RETIRED records are kept for post-hoc reconstruction —
``GET /flight/<id>`` on the exposition server (doc/observability.md)
or :meth:`FlightRecorder.timeline` in-process.

Design constraints, matching the rest of the telemetry plane:

* **host-side only** — events carry values the scheduler already has
  (``time.perf_counter`` stamps, slot ids, token counts); recording is
  an append under one lock, no device op anywhere.
* **bounded everywhere** — ``retain`` retired requests (FIFO ring,
  ``MXNET_SERVING_FLIGHT_RECORDER``, default 256; 0 disables), at most
  ``max_events`` events per request (overflow is counted, and the
  terminal ``retire`` event always lands), decode progress sampled
  every ``token_sample`` tokens rather than per token.
* **Chrome-trace export** — while a ``mx.telemetry.start_trace``
  capture is armed, every recorded event also emits an instant event
  (cat ``serving.flight``, the request id in ``args``), so flight
  timelines line up with the engine's prefill/decode spans in
  Perfetto.
"""
from __future__ import annotations

import collections
import threading
import time

from .. import telemetry as tele

__all__ = ["FlightRecorder"]


class _Flight:
    """One request's record: static metadata + the event list."""

    __slots__ = ("rid", "t0", "meta", "events", "dropped", "tokens")

    def __init__(self, rid, t0, meta):
        self.rid = rid
        self.t0 = t0
        self.meta = meta
        self.events = []
        self.dropped = 0
        self.tokens = 0


class FlightRecorder:
    """Bounded ring of per-request lifecycle timelines (one instance
    per :class:`~mxnet_tpu.serving.InferenceEngine`).

    ``retain``
        Retired requests kept for reconstruction (0 disables recording
        entirely — every method becomes a cheap no-op).
    ``max_events``
        Per-request event cap; past it events are dropped and counted
        (``dropped_events`` in the timeline), except the terminal
        ``retire`` event, which always lands.
    ``token_sample``
        Decode progress is recorded every this-many tokens (plus the
        first token, which gets its own ``first_token`` event from the
        engine) — a 2048-token generation leaves ~128 progress events,
        not 2048.
    """

    def __init__(self, retain=256, max_events=256, token_sample=16):
        self.retain = max(0, int(retain))
        self.max_events = max(8, int(max_events))
        self.token_sample = max(1, int(token_sample))
        self._live = {}                        # rid -> _Flight
        self._retired = collections.OrderedDict()   # FIFO ring
        self._lock = threading.Lock()

    @property
    def enabled(self):
        return self.retain > 0 and tele.enabled()

    # -- recording (engine thread) --------------------------------------
    def start(self, rid, **meta):
        """Open a record at submit time (``meta``: prompt_len,
        max_tokens, deadlines, resumed ...). Re-submitting an id that
        is still live restarts its record."""
        if not self.enabled:
            return
        now = time.perf_counter()
        with self._lock:
            self._live[rid] = fl = _Flight(rid, now, dict(meta))
        self._append(fl, now, "submit", meta or None)

    def event(self, rid, name, **args):
        """Record one lifecycle event for a live request (unknown ids
        are ignored — the recorder may have been disabled when the
        request was submitted)."""
        if not self.enabled:
            return
        with self._lock:
            fl = self._live.get(rid)
        if fl is not None:
            self._append(fl, time.perf_counter(), name, args or None)

    def token(self, rid, n):
        """Sampled decode progress: called once per drained token with
        the running count; records when the count CROSSES a
        ``token_sample`` boundary. Crossing, not ``n %% sample == 0``:
        a speculative verify round drains several accepted tokens at
        once, so the running count may skip over an exact multiple —
        the recorded event carries the true ``tokens=`` count either
        way (multi-token cadence correctness, doc/serving.md)."""
        if not self.enabled:
            return
        with self._lock:
            fl = self._live.get(rid)
        if fl is None:
            return
        prev = fl.tokens
        fl.tokens = n
        if n // self.token_sample > prev // self.token_sample:
            self._append(fl, time.perf_counter(), "decode",
                         {"tokens": n})

    def retire(self, rid, reason, **args):
        """Terminal event: moves the record to the retired ring
        (evicting the oldest past ``retain``). Always recorded, even
        at the event cap."""
        if not self.enabled:
            return
        now = time.perf_counter()
        with self._lock:
            fl = self._live.pop(rid, None)
            if fl is None:
                return
            fl.meta["retire_reason"] = reason
            self._retired[rid] = fl
            self._retired.move_to_end(rid)
            while len(self._retired) > self.retain:
                self._retired.popitem(last=False)
        args = dict(args)
        args["reason"] = reason
        self._append(fl, now, "retire", args, terminal=True)

    def _append(self, fl, now, name, args, terminal=False):
        ev = {"t_ms": round((now - fl.t0) * 1e3, 3), "event": name}
        if args:
            ev.update(args)
        with self._lock:
            if len(fl.events) >= self.max_events and not terminal:
                fl.dropped += 1
            else:
                fl.events.append(ev)
        if tele.tracing():
            tele.mark("serving.flight." + name, cat="serving.flight",
                      request=str(fl.rid), **(args or {}))

    # -- reconstruction (any thread) ------------------------------------
    def timeline(self, rid):
        """Full timeline of a live or recently-retired request:
        ``{"id", "live", "meta", "events", "dropped_events"}`` with
        event times in ms since submit — or None if the id was never
        recorded / already evicted from the ring."""
        with self._lock:
            fl = self._live.get(rid)
            live = fl is not None
            if fl is None:
                fl = self._retired.get(rid)
            if fl is None:
                return None
            return {"id": fl.rid, "live": live, "meta": dict(fl.meta),
                    "events": list(fl.events),
                    "dropped_events": fl.dropped}

    def records(self, rid):
        """Raw records for a request id, retired-then-live, each as
        ``(t0, events)`` with ``t0`` the ABSOLUTE ``perf_counter``
        stamp of the record's submit. A fleet router stitches these
        onto its own clock (``serving/fleet.py``): the same id can
        legitimately own TWO records at once on one engine — a
        prefill-role record retired with ``reason="handoff"`` plus the
        live decode-side record ``admit_handoff`` opened — and a
        failover resubmit restarts the live record, so the router
        copies events out as hops complete rather than referencing
        them in place."""
        with self._lock:
            out = []
            fl = self._retired.get(rid)
            if fl is not None:
                out.append((fl.t0, list(fl.events)))
            fl = self._live.get(rid)
            if fl is not None:
                out.append((fl.t0, list(fl.events)))
            return out

    def rows(self):
        """Summary rows for the retired ring (oldest first) — the
        "recently retired" half of the exposition server's
        ``/requests`` table."""
        now = time.perf_counter()
        with self._lock:
            return [{"id": fl.rid, "state": "retired",
                     "retire_reason": fl.meta.get("retire_reason"),
                     "prompt_len": fl.meta.get("prompt_len"),
                     "tokens": fl.tokens,
                     "age_s": round(now - fl.t0, 3),
                     "events": len(fl.events)}
                    for fl in self._retired.values()]

    def ids(self):
        """(live ids, retired ids oldest-first) currently recorded."""
        with self._lock:
            return list(self._live), list(self._retired)
