"""Host-side drafters for speculative decoding (doc/serving.md
"Speculative decoding").

Speculative decoding splits token generation into a cheap PROPOSE step
and an exact VERIFY step: a drafter guesses the next ``k`` tokens of a
sequence, the target model scores all ``k`` positions in ONE chunked
decode dispatch (``Decoder.verify_step_slots``), and the verified
prefix — every drafted token the target itself would have emitted,
plus the target's one corrected token — is accepted. Because the
target gates every emitted token, outputs are byte-identical to plain
decoding no matter what the drafter proposes; a bad drafter only costs
speed, never correctness (Leviathan et al. 2023).

This module holds the drafting side that runs on the HOST:
:class:`NgramDrafter` is a prompt-lookup / n-gram drafter (the
PLD/lookahead family) — no second model, no device op: it proposes the
continuation that followed the longest matching suffix of the request's
own ``prompt + emitted`` history. Few-shot prompts, code, and
self-repetitive generations accept most of its proposals for free.
The model-based drafter (a small draft LM sharing the slot-paged cache
layout) lives on the device side — ``Decoder.draft_propose_slots`` —
and is scheduled by the engine; see ``InferenceEngine(draft="model")``.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["NgramDrafter"]


class NgramDrafter:
    """Prompt-lookup (n-gram) drafter over one request's token history.

    Pure host-side state machine: ``context`` is the request's
    ``prompt + emitted tokens`` (the engine appends each drained
    token); :meth:`propose` returns up to ``k`` draft tokens by suffix
    matching — for n from ``max_ngram`` down to ``min_ngram``, find the
    LATEST earlier occurrence of the current n-token suffix and
    propose the tokens that followed it. Deterministic: the same
    context always proposes the same draft (the engine's byte-identity
    does not depend on it — verification gates every token — but
    determinism keeps accept-rate metrics reproducible).

    ``state()`` / ``from_state()`` round-trip the drafter through the
    engine's plain-JSON ``snapshot()`` (the context is derivable from
    the request's prompt + emitted tokens, so restore can also just
    rebuild it — the round-trip exists so external schedulers can
    persist drafters standalone).
    """

    def __init__(self, context=(), max_ngram=3, min_ngram=1):
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        if self.min_ngram < 1 or self.max_ngram < self.min_ngram:
            raise MXNetError(
                "NgramDrafter: need 1 <= min_ngram <= max_ngram, got "
                "min_ngram=%r max_ngram=%r" % (min_ngram, max_ngram))
        self._ctx = []
        # incremental n-gram index: for each n, gram -> (latest,
        # second-latest) start positions. propose() is O(max_ngram)
        # instead of re-scanning the whole context per call — this
        # runs per slot per decode round on the serving hot path, and
        # a backward scan would grow linearly with each request's
        # output. Second-latest matters because the query suffix is
        # itself the latest occurrence of its own gram.
        self._latest = [None] + [dict()
                                 for _ in range(self.max_ngram)]
        self._prev = [None] + [dict() for _ in range(self.max_ngram)]
        for t in context:
            self.append(t)

    def __len__(self):
        return len(self._ctx)

    def append(self, token):
        """One more emitted token (the engine calls this per drained
        token, keeping the context current through multi-token
        speculative drains)."""
        ctx = self._ctx
        ctx.append(int(token))
        j = len(ctx) - 1
        for n in range(1, self.max_ngram + 1):
            i = j - n + 1
            if i < 0:
                break
            gram = tuple(ctx[i:j + 1])
            old = self._latest[n].get(gram)
            if old is not None:
                self._prev[n][gram] = old
            self._latest[n][gram] = i

    def extend(self, tokens):
        for t in tokens:
            self.append(t)

    def propose(self, k):
        """Up to ``k`` draft tokens continuing the current context
        (always ``k`` on a match, possibly none).

        For n = ``max_ngram`` .. ``min_ngram``: take the last n tokens
        as the query suffix and scan for its LATEST earlier occurrence
        (an occurrence must leave at least one following token). The
        first n that matches wins — longer suffixes are stronger
        evidence. The proposal walks the tokens that followed the
        match; when the walk reaches the context end it steps back by
        the match's implied period and keeps going — a match at
        distance p from the suffix hypothesizes "the sequence repeats
        with period p", and extending the cycle is what keeps
        proposals ``k`` long on periodic tails (a LATEST-match run of
        one token, e.g. ``...c c c c``, would otherwise propose a
        single ``c`` and cap acceptance at 1 however large ``k``
        is)."""
        k = int(k)
        ctx = self._ctx
        L = len(ctx)
        if k < 1 or L < 2:
            return []
        for n in range(min(self.max_ngram, L - 1),
                       self.min_ngram - 1, -1):
            gram = tuple(ctx[L - n:])
            # the index's latest entry for the query gram is the query
            # suffix itself (appended last); the second-latest is the
            # latest EARLIER occurrence the scan used to find — and
            # any earlier start i <= L-n-1 leaves >= 1 follower token
            i = self._latest[n].get(gram)
            if i == L - n:
                i = self._prev[n].get(gram)
            if i is None or i + n >= L:
                continue
            period = L - n - i         # match-to-suffix distance
            out = []
            j = i + n
            for _ in range(k):
                if j >= L:
                    j -= period        # continue the cycle
                out.append(ctx[j])
                j += 1
            return out
        return []

    def state(self):
        """Plain-JSON snapshot of the drafter."""
        return {"context": list(self._ctx),
                "max_ngram": self.max_ngram,
                "min_ngram": self.min_ngram}

    @classmethod
    def from_state(cls, st):
        return cls(st["context"], max_ngram=st["max_ngram"],
                   min_ngram=st["min_ngram"])
