"""Fleet-level serving: N engines behind an admission router.

One :class:`~mxnet_tpu.serving.InferenceEngine` is production-shaped
but still a single point of failure: a dead replica loses every
in-flight request, and there is no way to take one out of rotation
for a deploy. :class:`FleetRouter` fronts N replicas (each itself
optionally tp-sharded) with the serving contract intact:

* **Health-driven routing** — submits are placed by each replica's
  live ``health()`` signals (the ``/healthz`` dict: closed / stuck /
  draining / queue depth / busy slots), with **prefix affinity**: the
  replica whose PR 5 prefix trie retains the longest prefix of the
  prompt wins placement (ties broken least-loaded), so shared-prefix
  traffic keeps landing where its K/V rows already live.
* **Transport discipline** (the PR 1 kvstore client's, repurposed for
  request traffic): channel ops carry a per-request timeout, bounded
  exponential backoff with jitter on retry, a ping heartbeat that
  tells a dead replica from a slow one, and ``(client_id, seq)``
  dedup so a caller's retried submit admits **exactly once** — at the
  router by the dedup table, at the replica by adopting an already-
  admitted request id instead of resubmitting it.
* **Failover** — a replica that dies mid-round (its ``step()``
  raises a non-engine error), trips its watchdog, or misses
  ``heartbeat_misses`` consecutive pings is declared dead: the router
  takes the PR 7 ``snapshot()`` of its host scheduler (valid after a
  crash — no device state), closes it, and resubmits every unfinished
  request on healthy peers with ``_resume_tokens``, so greedy
  continuations stay **byte-identical** to an uninterrupted run (the
  prefix cache absorbs the re-prefill where it hits).
* **Drain** (:meth:`FleetRouter.drain`) — the rolling-restart half:
  mark the replica ``draining`` (admission stops, ``/healthz``
  reports the state), migrate its in-flight requests to peers the
  same snapshot/resubmit way, close it. ``add_replica`` brings the
  restarted successor back into rotation. A capture replayed through
  a fleet under a rolling restart verifies byte-identical with zero
  failed requests (tools/replay_serving.py ``--replicas``).
* **Fleet-wide overload** — the PR 7 typed policies compose across
  replicas: a submit is tried against every healthy replica in
  placement order and only when ALL of them refuse does the router
  raise (typed :class:`EngineOverloaded` when the fleet is shedding,
  the generic backpressure ``MXNetError`` under ``block`` policies).
  Requests orphaned mid-migration (the restore target died too) wait
  in a router-side hold queue and re-place as replicas return.

Everything is host-side bookkeeping over the engines' public seams
(``submit``/``step``/``snapshot``/``health``/``close``); the compiled
program families and the per-replica compile-count contract are
untouched. The router mirrors the engine's driving surface
(``submit``/``step``/``serve_forever``/``queued``/``max_queue``/
``idle``/``health``), so ``tools/replay_serving.py`` replays a
capture through a fleet unchanged.

Knobs (constructor args override the ``MXNET_FLEET_*`` environment
defaults — doc/env_var.md): ``timeout_ms``, ``max_retries``,
``backoff_ms``, ``heartbeat_ms``, ``heartbeat_misses``.

**Disaggregated prefill/decode** (doc/serving.md "Disaggregated
prefill/decode"): with role-specialized replicas
(``InferenceEngine(role=...)``) the router places fresh prompts on
prefill/unified replicas only, collects each finished prefill's
:class:`~mxnet_tpu.serving.handoff.KVHandoff` package, and delivers it
to the least-loaded decode-capable replica — consulting decode-side
prefix affinity first, so a pool hit ships NO rows at all. Delivery
rides the same transport discipline as submits (timeout, bounded
retries, exactly-once via the target's import dedup), and losing
either specialist falls back to unified serving on the survivor (the
failover path widens its role).

Observability: ``fleet.failovers``, ``fleet.drains``,
``fleet.migrated_requests``, ``fleet.retries``, ``fleet.dedup_hits``,
``fleet.heartbeat_misses``, ``fleet.affinity_hits``,
``fleet.handoff_count``/``fleet.handoff_bytes`` counters, the
``fleet.handoff_ms`` histogram and the ``fleet.replicas_live`` gauge
(doc/observability.md); ``tools/dump_telemetry.py --fleet`` prints the
one-line summary.

**Fleet tracing plane** (doc/observability.md "Fleet tracing"): the
router mints a request-scoped trace context at :meth:`submit` — the
fleet request id plus a hop counter — and threads it through every
engine placement, the :class:`KVHandoff` wire format, and failover
resubmits, so each engine's flight record carries the fleet identity.
Its own :class:`FleetFlightRecorder` ring records the transitions the
fleet owns (``placed`` / ``in_transit`` / ``admitted`` / ``retried`` /
``failover`` / ``drained`` / ``migrated``) on the ABSOLUTE
``perf_counter`` clock and absorbs each per-engine flight record as
its hop completes, so ``FleetRouter.flight.timeline(trace_id)``
stitches one ordered cross-replica journey (``GET
/fleet/flight/<id>`` on the exposition server; ``?chrome=1`` exports
a Perfetto track-per-replica trace). End-to-end SLOs are measured
from ROUTER arrival and decomposed into ``router_queue / prefill /
handoff_wait / decode_admission / decode`` components that sum to the
end-to-end wall time by construction (the PR 13 phases-sum-to-wall
discipline): ``fleet.ttft_ms``/``fleet.cadence_ms`` histograms,
``fleet.slo_*`` attained/missed counters and multi-window burn gauges
(``telemetry.SloWindow``), all surfaced by ``GET /fleet``.

Fault injection: ``mxnet_tpu.testing.faults`` installs itself as
:data:`_FLEET_FAULTS` and drives the router's seams deterministically
(kill-replica-mid-round, heartbeat blackhole, slow replica, submit
failures) — tests/test_fleet.py and ``make chaos``.
"""
from __future__ import annotations

import collections
import contextlib
import os
import random
import threading
import time
import weakref

import numpy as np

from .. import telemetry as tele
from ..base import MXNetError
from .engine import (EngineClosed, EngineOverloaded, EngineStuck,
                     _TM_HANDOFF_WAIT)

__all__ = ["FleetRouter", "FleetRequest", "FleetFlightRecorder"]

# live routers, for the exposition server's /fleet plane (weak: a
# router the caller dropped must not be kept alive by telemetry)
_ROUTERS = weakref.WeakSet()

# FaultInjector hook point (mxnet_tpu.testing.faults installs itself
# here while a fleet fault plan is active)
_FLEET_FAULTS = None


def _timeout_s():
    """Per-channel-op timeout in seconds (MXNET_FLEET_TIMEOUT_MS): an
    op slower than this counts as a timeout and triggers the
    dead-vs-slow heartbeat probe before any resend."""
    return float(os.environ.get("MXNET_FLEET_TIMEOUT_MS", "1000")) / 1e3


def _max_retries():
    """Resend budget AFTER the first attempt (MXNET_FLEET_MAX_RETRIES)."""
    return int(os.environ.get("MXNET_FLEET_MAX_RETRIES", "3"))


def _backoff_base_s():
    """Base retry backoff in seconds (MXNET_FLEET_BACKOFF_MS)."""
    return float(os.environ.get("MXNET_FLEET_BACKOFF_MS", "5")) / 1e3


def _heartbeat_s():
    """Ping cadence per replica in seconds (MXNET_FLEET_HEARTBEAT_MS)."""
    return float(os.environ.get("MXNET_FLEET_HEARTBEAT_MS", "100")) / 1e3


def _heartbeat_misses():
    """Consecutive missed pings before a replica is declared dead
    (MXNET_FLEET_HEARTBEAT_MISSES)."""
    return int(os.environ.get("MXNET_FLEET_HEARTBEAT_MISSES", "3"))


_TM_FAILOVERS = tele.counter("fleet.failovers")
_TM_DRAINS = tele.counter("fleet.drains")
_TM_MIGRATED = tele.counter("fleet.migrated_requests")
_TM_RETRIES = tele.counter("fleet.retries")
_TM_DEDUP = tele.counter("fleet.dedup_hits")
_TM_HB_MISSES = tele.counter("fleet.heartbeat_misses")
_TM_AFFINITY = tele.counter("fleet.affinity_hits")
_TM_LIVE = tele.gauge("fleet.replicas_live")
# KV handoff (disaggregated prefill/decode): delivered packages, the
# bytes that actually shipped (0 for pool-hit skips), and per-delivery
# channel time; serving.handoff_wait_ms (engine module) gets the
# export-ready -> admitted wait observed here at delivery
_TM_HANDOFF_COUNT = tele.counter("fleet.handoff_count")
_TM_HANDOFF_BYTES = tele.counter("fleet.handoff_bytes")
_TM_HANDOFF_MS = tele.histogram("fleet.handoff_ms")
# End-to-end SLO accounting measured from ROUTER arrival (the engine's
# serving.ttft_ms starts at engine admission and restarts on every
# migration — the fleet figure is what the caller actually saw).
# Attainment counters tick once per request at the same host-side
# points that feed the histograms; the burn gauges are multi-window
# derivatives (tele.SloWindow), refreshed per step and per scrape.
# Declared with literal names so the metric catalog lint sees them.
_TM_FLEET_TTFT = tele.histogram("fleet.ttft_ms")
_TM_FLEET_CADENCE = tele.histogram("fleet.cadence_ms")
_TM_FLEET_SLO_TTFT_OK = tele.counter("fleet.slo_ttft_attained")
_TM_FLEET_SLO_TTFT_MISS = tele.counter("fleet.slo_ttft_missed")
_TM_FLEET_SLO_CAD_OK = tele.counter("fleet.slo_cadence_attained")
_TM_FLEET_SLO_CAD_MISS = tele.counter("fleet.slo_cadence_missed")
_FLEET_SLO_TTFT_WINDOWS = (
    (60.0, tele.gauge("fleet.slo_ttft_burn_1m")),
    (300.0, tele.gauge("fleet.slo_ttft_burn_5m")),
    (3600.0, tele.gauge("fleet.slo_ttft_burn_1h")))
_FLEET_SLO_CADENCE_WINDOWS = (
    (60.0, tele.gauge("fleet.slo_cadence_burn_1m")),
    (300.0, tele.gauge("fleet.slo_cadence_burn_5m")),
    (3600.0, tele.gauge("fleet.slo_cadence_burn_1h")))

# the five SLO decomposition components, in journey order; they sum to
# the end-to-end wall time by construction (``decode`` is the
# remainder, the PR 13 phases-sum-to-wall discipline)
_SLO_COMPONENTS = ("router_queue", "prefill", "handoff_wait",
                   "decode_admission", "decode")


class _FleetFlight:
    """One fleet request's stitched record: router/wire events plus
    the per-engine flight events absorbed as each hop completed.
    Events carry ABSOLUTE ``perf_counter`` stamps (``"t"``) and the
    scope that recorded them (``"router"`` or an engine id); rendering
    re-bases everything onto ``t0`` — the router submit — so one
    monotonic ``t_ms`` axis orders the whole cross-replica journey."""

    __slots__ = ("rid", "t0", "meta", "events", "dropped", "hops",
                 "absorbed")

    def __init__(self, rid, t0, meta):
        self.rid = rid
        self.t0 = t0
        self.meta = meta
        self.events = []
        self.dropped = 0
        self.hops = []          # engine ids, placement order
        self.absorbed = {}      # (engine_id, t0_us) -> events taken


class FleetFlightRecorder:
    """Bounded ring of stitched cross-replica request timelines — the
    fleet-level counterpart of :class:`~.flight.FlightRecorder`, same
    design constraints (host-side only, bounded everywhere, terminal
    event always lands).

    The router records its OWN transitions directly (placement,
    wire movement, retries, failover) and ABSORBS each engine's
    flight record when the request's hop there ends — engine records
    are keyed by request id and a failover resubmit or decode-side
    admission restarts/evicts them, so copying events out at hop
    boundaries is what makes the stitched journey survive the very
    faults it exists to explain. Event budget is per-request
    (``max_events``, terminal ``retire`` always lands); the ring keeps
    the last ``retain`` retired journeys."""

    def __init__(self, retain=256, max_events=512):
        self.retain = max(0, int(retain))
        self.max_events = max(8, int(max_events))
        self._live = {}                            # rid -> _FleetFlight
        self._retired = collections.OrderedDict()  # FIFO ring
        self._lock = threading.Lock()
        self._owner = None      # weakref to the router (set by it)

    @property
    def enabled(self):
        return self.retain > 0 and tele.enabled()

    # -- recording (router thread) --------------------------------------
    def start(self, rid, **meta):
        if not self.enabled:
            return
        now = time.perf_counter()
        with self._lock:
            self._live[rid] = fl = _FleetFlight(rid, now, dict(meta))
        self._append(fl, now, "router", "submit", meta or None)

    def event(self, rid, name, scope="router", **args):
        if not self.enabled:
            return
        with self._lock:
            fl = self._live.get(rid)
        if fl is not None:
            self._append(fl, time.perf_counter(), scope, name,
                         args or None)

    def hop(self, rid, engine_id):
        """Record a placement hop (consecutive duplicates collapse)."""
        if not self.enabled:
            return
        with self._lock:
            fl = self._live.get(rid)
            if fl is not None and (not fl.hops
                                   or fl.hops[-1] != engine_id):
                fl.hops.append(engine_id)

    def absorb(self, rid, engine_id, records):
        """Fold one engine's flight records for ``rid`` into the
        stitched journey. ``records`` is
        ``FlightRecorder.records(rid)`` — ``(t0, events)`` pairs with
        ABSOLUTE ``t0`` and per-record-relative event times.
        Idempotent per record: a record absorbed mid-life (a live
        ``timeline()`` query) and again at hop end only appends the
        events that arrived in between."""
        if not self.enabled:
            return
        with self._lock:
            fl = self._live.get(rid)
            if fl is None:
                return
            for t0, events in records:
                key = (engine_id, int(round(t0 * 1e6)))
                taken = fl.absorbed.get(key, 0)
                for ev in events[taken:]:
                    if len(fl.events) >= self.max_events:
                        fl.dropped += 1
                        continue
                    out = dict(ev)
                    out["t"] = t0 + out.pop("t_ms", 0.0) / 1e3
                    out["scope"] = engine_id
                    fl.events.append(out)
                fl.absorbed[key] = len(events)

    def retire(self, rid, reason, **args):
        """Terminal event: moves the journey to the retired ring.
        ``slo=`` (the decomposition dict) is folded into the record's
        meta so ``timeline()`` surfaces it without event spelunking."""
        if not self.enabled:
            return
        now = time.perf_counter()
        with self._lock:
            fl = self._live.pop(rid, None)
            if fl is None:
                return
            fl.meta["retire_reason"] = reason
            if "slo" in args:
                fl.meta["slo"] = args["slo"]
            self._retired[rid] = fl
            self._retired.move_to_end(rid)
            while len(self._retired) > self.retain:
                self._retired.popitem(last=False)
        args = dict(args)
        args["reason"] = reason
        self._append(fl, now, "router", "retire", args, terminal=True)

    def _append(self, fl, now, scope, name, args, terminal=False):
        ev = {"t": now, "scope": scope, "event": name}
        if args:
            for k, v in args.items():
                ev.setdefault(k, v)
        with self._lock:
            if len(fl.events) >= self.max_events and not terminal:
                fl.dropped += 1
            else:
                fl.events.append(ev)
        if tele.tracing():
            tele.mark("fleet.flight." + name, cat="fleet.flight",
                      request=str(fl.rid), scope=scope)

    # -- reconstruction (any thread) ------------------------------------
    def _get(self, rid):
        fl = self._live.get(rid)
        live = fl is not None
        if fl is None:
            fl = self._retired.get(rid)
        return fl, live

    def timeline(self, rid):
        """The stitched journey: ``{"id", "live", "meta", "hops",
        "events", "dropped_events"}``, events sorted on one monotonic
        clock with ``t_ms`` relative to ROUTER submit and ``scope``
        naming who recorded each one — or None if never recorded /
        evicted. Live queries first sweep the current replica's flight
        record so in-progress hops show up too."""
        owner = self._owner() if self._owner is not None else None
        if owner is not None:
            owner._absorb_live(rid)
        with self._lock:
            fl, live = self._get(rid)
            if fl is None:
                return None
            events = sorted(fl.events, key=lambda ev: ev["t"])
            out = []
            for ev in events:
                r = {"t_ms": round((ev["t"] - fl.t0) * 1e3, 3),
                     "scope": ev["scope"], "event": ev["event"]}
                r.update({k: v for k, v in ev.items()
                          if k not in ("t", "scope", "event")})
                out.append(r)
            return {"id": fl.rid, "live": live, "meta": dict(fl.meta),
                    "hops": list(fl.hops), "events": out,
                    "dropped_events": fl.dropped}

    def chrome_trace(self, rid):
        """Perfetto/chrome://tracing export of one stitched journey:
        one track ("thread") per scope — router first, then each
        engine in hop order — instant events for the journey, and the
        SLO decomposition rendered as back-to-back spans on the router
        track (they sum to end-to-end by construction, so the spans
        tile the request's wall time). Times in µs since router
        submit."""
        tl = self.timeline(rid)
        if tl is None:
            return None
        scopes = ["router"]
        for ev in tl["events"]:
            if ev["scope"] not in scopes:
                scopes.append(ev["scope"])
        tid = {s: i for i, s in enumerate(scopes)}
        evs = [{"name": "thread_name", "ph": "M", "pid": 0,
                "tid": tid[s], "args": {"name": s}} for s in scopes]
        for ev in tl["events"]:
            evs.append({
                "name": ev["event"], "ph": "i", "s": "t", "pid": 0,
                "tid": tid[ev["scope"]], "ts": ev["t_ms"] * 1e3,
                "cat": "fleet.flight",
                "args": {k: v for k, v in ev.items()
                         if k not in ("t_ms", "scope", "event")}})
        slo = tl["meta"].get("slo")
        if slo:
            t = 0.0
            for comp in _SLO_COMPONENTS:
                dur = float(slo.get(comp, 0.0))
                evs.append({"name": comp, "ph": "X", "pid": 0,
                            "tid": tid["router"], "ts": t * 1e3,
                            "dur": dur * 1e3, "cat": "fleet.slo",
                            "args": {"ms": dur}})
                t += dur
        return {"traceEvents": evs, "displayTimeUnit": "ms",
                "otherData": {"trace_id": str(tl["id"]),
                              "hops": tl["hops"]}}

    def rows(self):
        """Summary rows for the retired ring (oldest first)."""
        now = time.perf_counter()
        with self._lock:
            return [{"id": fl.rid, "state": "retired",
                     "retire_reason": fl.meta.get("retire_reason"),
                     "hops": list(fl.hops),
                     "age_s": round(now - fl.t0, 3),
                     "events": len(fl.events)}
                    for fl in self._retired.values()]

    def ids(self):
        with self._lock:
            return list(self._live), list(self._retired)


class FleetRequest:
    """Router-level request handle: delegates to the CURRENT underlying
    engine :class:`~mxnet_tpu.serving.Request` and is re-pointed when
    the request migrates (failover or drain), so the caller's handle
    survives any replica. While the request sits in the router's hold
    queue (every placement target refused — mid-migration limbo) the
    tokens drained before the migration stay readable.

    The surface mirrors what callers and ``tools/replay_serving.py``
    read off an engine handle: ``tokens``, ``done``, ``retire_reason``,
    ``result()``, ``resumed``, ``t_submit``/``t_first``/``t_done``,
    plus ``replica_id`` (where it lives now) and ``migrations``."""

    __slots__ = ("id", "client_key", "migrations", "resumed",
                 "_rec", "_cur", "_replica_id", "_t_submit", "_t_first",
                 "_deadline_abs", "_ttft_deadline_abs", "_error",
                 "_cancelled", "_hop", "_detached_from", "_t_place",
                 "_t_ready", "_t_deliver", "_admit_ms", "_ttft_seen",
                 "_finalized")

    def __init__(self, rid, rec, client_key=None):
        self.id = rid
        self.client_key = client_key
        self.migrations = 0
        # trace context: the fleet id IS the trace id; _hop counts
        # engine placements (0 = still router-side). The _t_* stamps
        # are the SLO decomposition breakpoints (doc/observability.md
        # "Fleet tracing"): first placement, handoff package ready,
        # handoff delivered, and the delivery channel-op cost.
        self._hop = 0
        self._detached_from = None
        self._t_place = None
        self._t_ready = None
        self._t_deliver = None
        self._admit_ms = None
        self._ttft_seen = None    # fleet TTFT observed (once)
        self._finalized = False   # fleet SLO/flight retirement ran
        # what replay() subtracts from the token count: the resume
        # prefix of the ORIGINAL fleet submit, never inflated by
        # migrations (migrated tokens were generated in this run)
        self.resumed = len(rec["tokens"])
        self._rec = rec            # resubmission record (snapshot shape)
        self._cur = None           # underlying Request, None while held
        self._replica_id = None
        now = time.perf_counter()
        self._t_submit = now
        self._t_first = None
        self._deadline_abs = None if rec.get("deadline_ms") is None \
            else now + rec["deadline_ms"] / 1e3
        self._ttft_deadline_abs = None \
            if rec.get("ttft_deadline_ms") is None \
            else now + rec["ttft_deadline_ms"] / 1e3
        self._error = None
        self._cancelled = False

    # -- delegation ---------------------------------------------------
    @property
    def tokens(self):
        if self._cur is not None:
            return self._cur.tokens
        return list(self._rec["tokens"])

    @property
    def done(self):
        if self._error is not None or self._cancelled:
            return True
        # "handoff" is a LOCAL retirement only: the prefill replica is
        # finished with the request, the fleet is not — the package is
        # in transit to a decode replica
        return self._cur is not None and self._cur.done \
            and self._cur.retire_reason != "handoff"

    @property
    def retire_reason(self):
        if self._error is not None:
            return "shed" if isinstance(self._error, EngineOverloaded) \
                else "error"
        if self._cancelled:
            return "cancelled"
        if self._cur is None or self._cur.retire_reason == "handoff":
            return None
        return self._cur.retire_reason

    @property
    def replica_id(self):
        return self._replica_id

    @property
    def t_submit(self):
        return self._t_submit

    @property
    def t_first(self):
        if self._t_first is not None:
            return self._t_first
        return None if self._cur is None else self._cur.t_first

    @property
    def t_done(self):
        return None if self._cur is None else self._cur.t_done

    @property
    def prefix_hit_tokens(self):
        return 0 if self._cur is None \
            else getattr(self._cur, "prefix_hit_tokens", 0)

    def result(self):
        """The emitted tokens (resume prefix included), or the typed
        error this request was retired with — same contract as
        ``Request.result()``, across however many replicas served it."""
        if self._error is not None:
            raise self._error
        if self._cur is None:
            if self._cancelled:
                return np.asarray(self._rec["tokens"], np.int64)
            raise MXNetError(
                "FleetRequest %r is awaiting re-placement (every "
                "replica refused; step() the router)" % (self.id,))
        return self._cur.result()

    # -- router internals ---------------------------------------------
    def _submit_kwargs(self, now):
        """Engine-submit kwargs for (re)placement: deadlines are kept
        ABSOLUTE at the router so time spent held or migrating never
        refreshes a request's budget."""
        kw = dict(
            max_tokens=self._rec["max_tokens"],
            eos_id=self._rec["eos_id"],
            temperature=self._rec["temperature"],
            seed=self._rec["seed"],
            request_id=self.id,
            _resume_tokens=tuple(self._rec["tokens"]),
            _trace=(self.id, self._hop + 1),
        )
        if self._deadline_abs is not None:
            kw["deadline_ms"] = (self._deadline_abs - now) * 1e3
        if self._ttft_deadline_abs is not None and self._t_first is None:
            kw["ttft_deadline_ms"] = \
                (self._ttft_deadline_abs - now) * 1e3
        return kw

    def _point_at(self, req, replica_id):
        self._cur = req
        self._replica_id = replica_id
        trace = getattr(req, "trace", None)
        self._hop = trace[1] if trace is not None else self._hop + 1
        if self._rec["seed"] is None:      # engine drew it: pin for
            self._rec["seed"] = int(req.seed)   # any later migration

    def _unhook(self, snap_rec):
        """Detach from a dying replica: absorb the snapshot record
        (authoritative token prefix + remaining budgets) and remember
        the first-token time — the old underlying handle is about to
        be retired by ``close()`` and must not speak for us."""
        if self._cur is not None and self._cur.t_first is not None \
                and self._t_first is None:
            self._t_first = self._cur.t_first
        self._rec = dict(self._rec, tokens=list(snap_rec["tokens"]))
        self._cur = None
        self._replica_id = None

    def __repr__(self):
        return ("FleetRequest(id=%r, replica=%r, tokens=%d, "
                "migrations=%d, done=%r)"
                % (self.id, self._replica_id, len(self.tokens),
                   self.migrations, self.done))


class _Replica:
    """Router-side bookkeeping for one managed engine."""

    __slots__ = ("engine", "id", "alive", "misses", "last_hb", "order")

    def __init__(self, engine, order):
        self.engine = engine
        self.id = engine.engine_id
        self.alive = True
        self.misses = 0
        self.last_hb = -float("inf")
        self.order = order


class FleetRouter:
    """Admission router over N :class:`InferenceEngine` replicas —
    module docstring has the full contract. Drive it exactly like one
    engine: ``submit()`` + ``step()`` (or ``serve_forever()``);
    ``close()`` shuts the whole fleet down."""

    def __init__(self, engines, timeout_ms=None, max_retries=None,
                 backoff_ms=None, heartbeat_ms=None,
                 heartbeat_misses=None, seed=0,
                 slo_ttft_ms=None, slo_cadence_ms=None, slo_target=0.99,
                 flight_recorder=None):
        engines = list(engines)
        if not engines:
            raise MXNetError("FleetRouter: need at least one replica")
        # end-to-end SLO thresholds, measured from ROUTER arrival
        # (constructor-only: per-engine MXNET_SERVING_SLO_* knobs keep
        # meaning the engine-local figures)
        self.slo_ttft_ms = None if slo_ttft_ms is None \
            else float(slo_ttft_ms)
        self.slo_cadence_ms = None if slo_cadence_ms is None \
            else float(slo_cadence_ms)
        self.slo_target = float(slo_target)
        self._slo_windows = {}
        self.flight = flight_recorder if flight_recorder is not None \
            else FleetFlightRecorder()
        self.flight._owner = weakref.ref(self)
        self.timeout_ms = float(timeout_ms) if timeout_ms is not None \
            else _timeout_s() * 1e3
        self.max_retries = int(max_retries) if max_retries is not None \
            else _max_retries()
        self.backoff_s = (float(backoff_ms) / 1e3) \
            if backoff_ms is not None else _backoff_base_s()
        self.heartbeat_s = (float(heartbeat_ms) / 1e3) \
            if heartbeat_ms is not None else _heartbeat_s()
        self.heartbeat_misses = int(heartbeat_misses) \
            if heartbeat_misses is not None else _heartbeat_misses()
        if self.max_retries < 0 or self.heartbeat_misses < 1:
            raise MXNetError("FleetRouter: max_retries must be >= 0 "
                             "and heartbeat_misses >= 1")
        self._rng = random.Random(seed)    # backoff jitter (seeded:
        self._replicas = {}                # deterministic tests)
        self._order = 0
        self._requests = {}                # id -> FleetRequest (live)
        self._held = collections.deque()   # awaiting re-placement
        self._handoffs = collections.deque()  # (pkg, fr) in transit
        self._dedup = {}                   # (client_id, seq) -> handle
        self._next_id = 0
        self._closed = False
        self.stats = collections.defaultdict(int)
        for e in engines:
            self.add_replica(e)
        _ROUTERS.add(self)

    # -- replica set ----------------------------------------------------
    def add_replica(self, engine):
        """Bring a (fresh or restarted) engine into rotation. Held
        requests re-place onto it on the next :meth:`step`."""
        self._check_open()
        if getattr(engine, "_closed", False):
            raise MXNetError("FleetRouter: replica %r is closed"
                             % (getattr(engine, "engine_id", engine),))
        rid = engine.engine_id
        old = self._replicas.get(rid)
        if old is not None and old.alive:
            raise MXNetError("FleetRouter: replica id %r is already "
                             "in rotation" % (rid,))
        self._replicas[rid] = _Replica(engine, self._order)
        self._order += 1
        _TM_LIVE.set(len(self._live()))
        return rid

    def replica(self, rid):
        rep = self._replicas.get(rid)
        return None if rep is None else rep.engine

    def replica_ids(self, live_only=False):
        if live_only:
            return [r.id for r in self._live()]
        return list(self._replicas)

    def _live(self):
        return [r for r in self._replicas.values()
                if r.alive and not r.engine._closed]

    def _candidates(self):
        """Replicas admission may target: alive, not draining, not
        stuck, not closed — the health() signals a real fleet would
        scrape off each replica's /healthz."""
        out = []
        for r in self._live():
            h = r.engine.health()
            if h.get("draining") or h.get("stuck"):
                continue
            out.append(r)
        return out

    # -- engine-mirroring surface ---------------------------------------
    @property
    def max_queue(self):
        """Aggregate admission capacity (live replicas' max_queue sum;
        at least 1 so a replica-less interregnum doesn't zero the
        backpressure check into a busy loop)."""
        return max(1, sum(r.engine.max_queue for r in self._live()))

    def queued(self):
        return sum(r.engine.queued() for r in self._live()) \
            + len(self._held)

    @property
    def weight_dtype(self):
        """The fleet's weight-storage dtype (replicas are uniform;
        replay's auto verify-mode keys off it)."""
        live = self._live()
        return live[0].engine.weight_dtype if live else "float"

    @property
    def idle(self):
        # a package awaiting delivery (router-side or still inside a
        # prefill replica's outbox) is outstanding work: the fleet
        # must keep stepping until it lands or falls back
        if self._held or self._handoffs:
            return False
        for r in self._live():
            if not r.engine.idle or r.engine._handoff_out:
                return False
        return True

    def health(self):
        """Fleet liveness: per-replica ``health()`` dicts (dead ones
        abbreviated) plus router-level queue state."""
        reps = {}
        for r in self._replicas.values():
            if r.alive and not r.engine._closed:
                reps[r.id] = r.engine.health()
            else:
                reps[r.id] = {"closed": True, "dead": True}
        return {
            "closed": self._closed,
            "replicas": reps,
            "replicas_live": len(self._live()),
            "held": len(self._held),
            "handoffs_in_transit": len(self._handoffs),
        }

    def _check_open(self):
        if self._closed:
            raise EngineClosed("FleetRouter is closed")

    # -- admission ------------------------------------------------------
    def submit(self, prompt, max_tokens, eos_id=None, temperature=0.0,
               seed=None, request_id=None, deadline_ms=None,
               ttft_deadline_ms=None, client_id=None, seq=None,
               _resume_tokens=()):
        """Route one request to a healthy replica; returns its
        :class:`FleetRequest` handle.

        ``(client_id, seq)`` is the exactly-once identity for callers
        that RETRY a submit after an ambiguous failure (their channel
        to the router timed out): a resubmission with the same pair
        returns the original handle instead of admitting twice — the
        PR 1 kvstore dedup discipline applied to request traffic.
        Both-or-neither; ids are per-client monotonic sequence
        numbers.

        Placement prefers the replica whose prefix cache retains the
        longest prefix of ``prompt`` (affinity), then the least
        loaded. A replica that refuses (typed shed or block
        backpressure) is skipped; only when EVERY healthy replica
        refuses does the router raise — typed
        :class:`EngineOverloaded` if the fleet is shedding, else the
        generic backpressure error."""
        self._check_open()
        if (client_id is None) != (seq is None):
            raise MXNetError("FleetRouter: client_id and seq must be "
                             "passed together")
        key = None
        if client_id is not None:
            key = (client_id, int(seq))
            prev = self._dedup.get(key)
            if prev is not None:
                self.stats["dedup_hits"] += 1
                _TM_DEDUP.inc()
                return prev
        rid = request_id
        if rid is None:
            rid = "f%d" % self._next_id
            self._next_id += 1
        rec = {
            "prompt": np.asarray(prompt),
            "tokens": list(_resume_tokens),
            "max_tokens": max_tokens,
            "eos_id": eos_id,
            "temperature": temperature,
            "seed": seed,
            "deadline_ms": deadline_ms,
            "ttft_deadline_ms": ttft_deadline_ms,
        }
        fr = FleetRequest(rid, rec, client_key=key)
        if self.flight.enabled:
            meta = {"prompt_len": int(rec["prompt"].size),
                    "max_tokens": int(max_tokens)}
            if rec["tokens"]:
                meta["resumed"] = len(rec["tokens"])
            if deadline_ms is not None:
                meta["deadline_ms"] = deadline_ms
            if ttft_deadline_ms is not None:
                meta["ttft_deadline_ms"] = ttft_deadline_ms
            self.flight.start(rid, **meta)
        try:
            self._place_new(fr)
        except Exception:
            # fleet-wide refusal: the journey ends at the router
            self.flight.retire(rid, "refused")
            raise
        self._requests[rid] = fr
        if key is not None:
            self._dedup[key] = fr
        self.stats["submitted"] += 1
        return fr

    def _place_new(self, fr):
        """First placement of a fresh submit: raise on fleet-wide
        refusal (migrations use :meth:`_try_place` and hold instead)."""
        shed_err, block_err = None, None
        for rep in self._ranked(fr):
            try:
                req = self._channel_submit(rep, fr)
            except EngineOverloaded as e:
                shed_err = e
                continue
            except EngineClosed:
                self._fail_over(rep, "closed underneath the router")
                continue
            except ConnectionError:
                self._fail_over(rep, "channel dead")
                continue
            except MXNetError as e:
                if "queue is full" in str(e):
                    block_err = e          # block-policy backpressure
                    continue
                raise                      # validation error: caller bug
            fr._point_at(req, rep.id)
            fr._t_place = time.perf_counter()
            self.flight.hop(fr.id, rep.id)
            self.flight.event(fr.id, "placed", replica=rep.id,
                              reason=self._place_reason(rep, fr),
                              hop=fr._hop)
            return
        if shed_err is not None:
            raise EngineOverloaded(
                "FleetRouter: fleet-wide overload — every healthy "
                "replica shed (last: %s)" % (shed_err,))
        if block_err is not None:
            raise MXNetError(
                "FleetRouter: every healthy replica's queue is full "
                "(block policy) — step() the router to drain")
        raise MXNetError("FleetRouter: no healthy replica to admit "
                         "request %r (live=%d)"
                         % (fr.id, len(self._live())))

    def _ranked(self, fr):
        """Placement order: deepest prefix-affinity first, then least
        loaded, then rotation order. Counts an affinity hit when a
        retained prefix actually decided placement. Decode specialists
        never take fresh prompts — their whole point is to never trace
        a prefill program."""
        cands = [r for r in self._candidates()
                 if getattr(r.engine, "role", "unified") != "decode"]
        if not cands:
            return []
        prompt = fr._rec["prompt"]
        scored = []
        for rep in cands:
            h = rep.engine.health()
            load = h.get("queued", 0) + h.get("slots_busy", 0)
            scored.append((-self._affinity(rep.engine, prompt),
                           load, rep.order, rep))
        scored.sort(key=lambda t: t[:3])
        if scored and scored[0][0] < 0:
            self.stats["affinity_hits"] += 1
            _TM_AFFINITY.inc()
        return [t[3] for t in scored]

    def _place_reason(self, rep, fr):
        """Why placement chose this replica, for the ``placed`` flight
        event: a retained prompt prefix → ``affinity``, a prefill
        specialist → ``role``, otherwise plain ``least_loaded``."""
        if self._affinity(rep.engine, fr._rec["prompt"]) > 0:
            return "affinity"
        if getattr(rep.engine, "role", "unified") == "prefill":
            return "role"
        return "least_loaded"

    @staticmethod
    def _affinity(engine, prompt):
        """Longest retained prefix of ``prompt`` in the replica's
        trie — a PLACEMENT HINT only: no LRU touch, no pin (the
        engine re-walks at admission and takes the hit itself)."""
        pc = getattr(engine, "_prefix", None)
        if pc is None or not len(prompt):
            return 0
        node, depth = pc._root, 0
        for t in prompt:
            child = node.children.get(int(t))
            if child is None:
                break
            node, depth = child, depth + 1
        return depth

    def _channel_submit(self, rep, fr, migration=False):
        """One admission over the replica channel, with the PR 1
        transport discipline: per-op timeout, bounded exponential
        backoff + jitter on retry, ping-probe after a timeout to tell
        dead from slow, and exactly-once adoption — a retried submit
        whose first attempt DID land (the reply was what got lost)
        finds the admitted request by id instead of double-admitting.
        Raises ``ConnectionError`` when the budget is exhausted;
        ``migration=True`` lifts ``max_queue`` for the one submit
        (migrated work was already admitted fleet-wide and must never
        shed — the PR 7 ``restore()`` discipline)."""
        eng = rep.engine
        backoff = self.backoff_s
        last_err = None
        for attempt in range(self.max_retries + 1):
            flt = _FLEET_FAULTS
            try:
                if flt is not None:
                    delay = flt.fleet_submit(rep.id)
                    if delay and delay * 1e3 > self.timeout_ms:
                        raise TimeoutError(
                            "fleet channel: submit to %r exceeded "
                            "timeout_ms=%g" % (rep.id, self.timeout_ms))
                kw = fr._submit_kwargs(time.perf_counter())
                if migration:
                    real_mq = eng.max_queue
                    eng.max_queue = max(real_mq, eng.queued() + 1)
                    try:
                        return eng.submit(fr._rec["prompt"], **kw)
                    finally:
                        eng.max_queue = real_mq
                return eng.submit(fr._rec["prompt"], **kw)
            except (ConnectionError, TimeoutError) as e:
                last_err = e
                # the first attempt may have landed before the fault
                # (lost-reply case): adopt it — exactly-once admission
                existing = eng._active.get(fr.id)
                if existing is not None:
                    return existing
                alive = isinstance(e, TimeoutError) \
                    and self._ping(rep)
                if attempt >= self.max_retries:
                    raise ConnectionError(
                        "fleet channel: replica %r %s after %d "
                        "attempt(s) (%s)"
                        % (rep.id,
                           "is alive but slow" if alive
                           else "is unreachable or died",
                           attempt + 1, e))
                self.stats["retries"] += 1
                _TM_RETRIES.inc()
                self.flight.event(fr.id, "retried", replica=rep.id,
                                  op="submit", attempt=attempt + 1)
                if not alive:
                    delay = backoff * (2 ** attempt)
                    time.sleep(min(
                        delay * (0.5 + self._rng.random()), 0.5))
        raise ConnectionError("fleet channel: replica %r failed (%s)"
                              % (rep.id, last_err))  # pragma: no cover

    # -- KV handoff (disaggregated prefill/decode) ----------------------
    @staticmethod
    def _pool_covers(engine, pkg):
        """Does this replica's prefix pool retain the package's FULL
        prefill? Then delivery ships identity only — the target copies
        the rows out of its own pool (peek: no LRU touch, no pin; the
        engine re-walks and pins at admission)."""
        pc = getattr(engine, "_prefix", None)
        if pc is None:
            return False
        return pc.peek(pkg.prefill_seq) >= pkg.prefill_len

    def _ranked_decode(self, pkg):
        """Delivery order for one package: decode-capable replicas
        (never prefill specialists), full-pool-affinity first — a hit
        skips the row transfer entirely — then least loaded, then
        rotation order."""
        scored = []
        for rep in self._candidates():
            if getattr(rep.engine, "role", "unified") == "prefill":
                continue
            h = rep.engine.health()
            load = h.get("queued", 0) + h.get("slots_busy", 0)
            scored.append((0 if self._pool_covers(rep.engine, pkg)
                           else 1, load, rep.order, rep))
        scored.sort(key=lambda t: t[:3])
        if scored and scored[0][0] == 0:
            self.stats["affinity_hits"] += 1
            _TM_AFFINITY.inc()
        return [t[3] for t in scored]

    def _collect_handoffs(self):
        """Sweep every live replica's handoff outbox into the router's
        in-transit queue. Packages whose fleet handle already retired
        (cancelled / errored while the prefill ran) resolve on the
        spot — the source slot frees, nothing ships."""
        for rep in list(self._replicas.values()):
            if not rep.alive or rep.engine._closed \
                    or not rep.engine._handoff_out:
                continue
            for pkg in rep.engine.take_handoffs():
                fr = self._requests.get(pkg.id)
                if fr is None or fr.done:
                    with contextlib.suppress(Exception):
                        pkg.resolve()
                    continue
                # the prefill hop is over: pin the first-token stamp
                # before _point_at re-points the handle at a decode
                # request whose t_first is its own admission time, and
                # absorb the prefill engine's flight record while its
                # retired ring still holds it
                if fr._t_first is None and fr._cur is not None \
                        and fr._cur.t_first is not None:
                    fr._t_first = fr._cur.t_first
                fr._t_ready = pkg.t_ready
                self._absorb_hop(fr, rep)
                self.flight.event(
                    fr.id, "in_transit",
                    **{"from": rep.id, "prefill_len": pkg.prefill_len})
                self._handoffs.append((pkg, fr))

    def _channel_handoff(self, rep, pkg, fr):
        """Deliver one package over the replica channel with the same
        transport discipline as ``_channel_submit``: per-op timeout
        (the ``fleet_handoff`` fault hook is the injected network),
        bounded backoff + jitter, ping-probe after a timeout, and
        exactly-once — a retried delivery whose first attempt landed
        finds the admitted request by id on the target (the target's
        own import dedup backs this up). Returns ``(request,
        shipped_bytes, pool_hit)``; raises ``ConnectionError`` when
        the budget is exhausted."""
        eng = rep.engine
        skip = self._pool_covers(eng, pkg)
        kw = {}
        if fr._deadline_abs is not None:
            kw["deadline_ms"] = \
                (fr._deadline_abs - time.perf_counter()) * 1e3
        backoff = self.backoff_s
        last_err = None
        for attempt in range(self.max_retries + 1):
            flt = _FLEET_FAULTS
            try:
                if flt is not None:
                    delay = flt.fleet_handoff(rep.id)
                    if delay and delay * 1e3 > self.timeout_ms:
                        raise TimeoutError(
                            "fleet channel: KV handoff to %r exceeded "
                            "timeout_ms=%g" % (rep.id, self.timeout_ms))
                t0 = time.perf_counter()
                req = eng.admit_handoff(pkg.payload(with_rows=not skip),
                                        **kw)
                fr._admit_ms = (time.perf_counter() - t0) * 1e3
                _TM_HANDOFF_MS.observe(fr._admit_ms)
                return req, (0 if skip else pkg.nbytes), skip
            except (ConnectionError, TimeoutError) as e:
                last_err = e
                existing = eng._active.get(pkg.id)
                if existing is not None:
                    return existing, (0 if skip else pkg.nbytes), skip
                alive = isinstance(e, TimeoutError) \
                    and self._ping(rep)
                if attempt >= self.max_retries:
                    raise ConnectionError(
                        "fleet channel: replica %r %s after %d handoff "
                        "attempt(s) (%s)"
                        % (rep.id,
                           "is alive but slow" if alive
                           else "is unreachable or died",
                           attempt + 1, e))
                self.stats["retries"] += 1
                _TM_RETRIES.inc()
                self.flight.event(fr.id, "retried", replica=rep.id,
                                  op="handoff", attempt=attempt + 1)
                if not alive:
                    delay = backoff * (2 ** attempt)
                    time.sleep(min(
                        delay * (0.5 + self._rng.random()), 0.5))
        raise ConnectionError(
            "fleet channel: replica %r failed handoff (%s)"
            % (rep.id, last_err))  # pragma: no cover

    def _deliver_handoffs(self):
        """One delivery pass over the in-transit queue. Each package
        tries every decode-capable replica in affinity/load order; all
        slots busy → it keeps waiting (serving.handoff_wait_ms is
        exactly this wait); NO decode-capable replica left → unified
        fallback: the package is abandoned and the request re-prefills
        on whatever survives via the hold queue, byte-identically."""
        fell_back = False
        for _ in range(len(self._handoffs)):
            pkg, fr = self._handoffs.popleft()
            if pkg.resolved:
                continue
            if fr.done or fr._cur is None \
                    or fr._cur.retire_reason != "handoff":
                # cancelled, errored, or already re-placed (the
                # source failed over and the fallback path took it):
                # this package has nothing left to deliver
                with contextlib.suppress(Exception):
                    pkg.resolve()
                continue
            placed = False
            for rep in self._ranked_decode(pkg):
                try:
                    req, nbytes, pool_hit = \
                        self._channel_handoff(rep, pkg, fr)
                except EngineOverloaded:
                    continue               # no free slot: next replica
                except EngineClosed:
                    self._fail_over(rep, "closed underneath the router")
                    continue
                except ConnectionError:
                    # the journey's delivery target died under it —
                    # record that on the stitched timeline (the request
                    # itself was never resident there, so _fail_over's
                    # per-request _detach sweep won't see it)
                    self.flight.event(
                        fr.id, "failover",
                        reason="target died in transit",
                        **{"from": rep.id,
                           "resume_len": len(pkg.tokens)})
                    self._fail_over(rep, "channel dead during KV "
                                         "handoff")
                    continue
                except MXNetError:
                    continue               # refused (geometry/stale)
                _TM_HANDOFF_WAIT.observe(
                    (time.perf_counter() - pkg.t_ready) * 1e3)
                fr._t_deliver = time.perf_counter()
                fr._point_at(req, rep.id)
                self.flight.hop(fr.id, rep.id)
                self.flight.event(
                    fr.id, "admitted", replica=rep.id,
                    bytes=int(nbytes), pool_hit=bool(pool_hit),
                    dtype=getattr(pkg.source, "handoff_dtype",
                                  "native"),
                    hop=fr._hop)
                pkg.resolve()
                self.stats["handoffs"] += 1
                _TM_HANDOFF_COUNT.inc()
                if pool_hit:
                    self.stats["handoff_pool_hits"] += 1
                else:
                    self.stats["handoff_bytes"] += nbytes
                    _TM_HANDOFF_BYTES.inc(nbytes)
                placed = True
                break
            if placed:
                continue
            if any(getattr(r.engine, "role", "unified") != "prefill"
                   for r in self._candidates()):
                # decode capacity exists but is full right now: keep
                # waiting (the wait histogram is measuring this)
                self._handoffs.append((pkg, fr))
            else:
                with contextlib.suppress(Exception):
                    pkg.resolve()
                fr._unhook({"tokens": pkg.tokens})
                fr._detached_from = pkg.source.engine_id
                self._held.append(fr)
                self.stats["handoff_fallbacks"] += 1
                self.flight.event(
                    fr.id, "failover", reason="no decode capacity",
                    **{"from": pkg.source.engine_id,
                       "resume_len": len(pkg.tokens)})
                fell_back = True
        if fell_back:
            self._ensure_roles()
            self._drain_held()

    def _abandon_handoffs(self, rep):
        """A replica is dying: packages IT exported cannot deliver
        (their rows live in its cache) — unhook their requests onto
        the hold queue for a unified re-prefill on the survivors."""
        for _ in range(len(self._handoffs)):
            pkg, fr = self._handoffs.popleft()
            if pkg.source is not rep.engine:
                self._handoffs.append((pkg, fr))
                continue
            with contextlib.suppress(Exception):
                pkg.resolve()
            if not fr.done:
                fr._unhook({"tokens": pkg.tokens})
                fr._detached_from = rep.id
                self._held.append(fr)
                self.stats["handoff_fallbacks"] += 1
                self.flight.event(
                    fr.id, "failover", reason="source died in transit",
                    **{"from": rep.id,
                       "resume_len": len(pkg.tokens)})

    def _ensure_roles(self):
        """Failover role repair: when the fleet has lost every replica
        of one phase (all survivors are the same specialist), widen
        the least-loaded survivor to unified so both phases keep
        serving — the missing program family compiles lazily on first
        use. No-op while a unified replica or both specialists are
        live."""
        live = self._live()
        roles = {getattr(r.engine, "role", "unified") for r in live}
        if not live or "unified" in roles \
                or ("prefill" in roles and "decode" in roles):
            return

        def load(r):
            h = r.engine.health()
            return (h.get("queued", 0) + h.get("slots_busy", 0),
                    r.order)

        target = min(live, key=load)
        with contextlib.suppress(Exception):
            target.engine.set_role("unified")
            self.stats["role_promotions"] += 1

    # -- heartbeats / liveness ------------------------------------------
    def _ping(self, rep):
        """One heartbeat probe: False = no answer (a blackholed or
        dead peer), True = alive (possibly slow/stuck — health() says
        which). In-process the 'network' is the fault injector."""
        flt = _FLEET_FAULTS
        if flt is not None and flt.fleet_ping_blackholed(rep.id):
            return False
        return rep.alive and not rep.engine._closed

    def _heartbeat(self, rep):
        if self._ping(rep):
            rep.misses = 0
            return
        rep.misses += 1
        self.stats["heartbeat_misses"] += 1
        _TM_HB_MISSES.inc()
        if rep.misses >= self.heartbeat_misses:
            self._fail_over(rep, "%d consecutive heartbeat misses"
                            % rep.misses)

    # -- failover / drain -----------------------------------------------
    def _fail_over(self, rep, reason):
        """Declare ``rep`` dead and migrate its unfinished requests to
        peers: snapshot the host scheduler (valid after a crash or
        watchdog trip — PR 7), close the corpse, resubmit every
        request with its token prefix so continuations stay
        byte-identical. Requests no peer can take right now wait in
        the hold queue."""
        if not rep.alive:
            return
        rep.alive = False
        _TM_LIVE.set(len(self._live()))
        self.stats["failovers"] += 1
        _TM_FAILOVERS.inc()
        try:
            snap = rep.engine.snapshot()
        except Exception:
            snap = {"requests": []}
        # in-transit packages this replica exported die with it (their
        # rows live in its cache); packages still in its outbox ride
        # the snapshot into _detach — disjoint sets, no double-hold
        self._abandon_handoffs(rep)
        self._detach(snap, rep, event="failover")
        with contextlib.suppress(Exception):
            rep.engine.close()
        self._ensure_roles()
        self._drain_held()

    def drain(self, replica):
        """Take one replica out of rotation for a deploy, migrating
        its in-flight work live (doc/fault_tolerance.md "Fleet
        resilience" has the runbook): admission stops first (the
        engine reports ``draining`` on ``/healthz``), then the
        snapshot/resubmit migration runs and the replica is closed.
        Pass the engine or its ``engine_id``; returns the snapshot
        that was migrated (what an operator would archive). Restart
        with :meth:`add_replica`."""
        self._check_open()
        rid = getattr(replica, "engine_id", replica)
        rep = self._replicas.get(rid)
        if rep is None or not rep.alive or rep.engine._closed:
            raise MXNetError("FleetRouter.drain: %r is not a live "
                             "replica" % (rid,))
        rep.engine.draining = True       # stop admission; /healthz
        snap = rep.engine.snapshot()     # reports "draining"
        rep.alive = False
        _TM_LIVE.set(len(self._live()))
        self.stats["drains"] += 1
        _TM_DRAINS.inc()
        self._abandon_handoffs(rep)
        self._detach(snap, rep, event="drained")
        with contextlib.suppress(Exception):
            rep.engine.close()
        self._ensure_roles()
        self._drain_held()
        return snap

    def _detach(self, snap, rep=None, event="failover"):
        """Re-point every fleet handle off a dying replica onto the
        hold queue, snapshot record absorbed (token prefix + remaining
        deadline budgets). The dying engine's flight records are
        absorbed FIRST — ``close()`` is about to retire them with
        reasons that belong to the corpse, and the resubmit on a peer
        will reuse the request id."""
        for r in snap.get("requests", ()):
            fr = self._requests.get(r["id"])
            if fr is None or fr.done:
                continue
            if rep is not None:
                self._absorb_hop(fr, rep)
                fr._detached_from = rep.id
            fr._unhook(r)
            self._held.append(fr)
            self.flight.event(
                fr.id, event,
                **{"from": None if rep is None else rep.id,
                   "resume_len": len(r.get("tokens", ()))})

    def _drain_held(self):
        """One re-placement pass over the hold queue (each held
        request tried once; failures keep waiting — a later step or
        add_replica retries)."""
        for _ in range(len(self._held)):
            if not self._held:
                break
            fr = self._held.popleft()
            if fr.done:
                continue
            if self._try_place(fr):
                fr.migrations += 1
                self.stats["migrated_requests"] += 1
                _TM_MIGRATED.inc()
            else:
                self._held.append(fr)

    def _try_place(self, fr):
        """Best-effort migration placement: refusals hold instead of
        raising (the work was already admitted fleet-wide)."""
        for rep in self._ranked(fr):
            try:
                req = self._channel_submit(rep, fr, migration=True)
            except (EngineOverloaded, EngineClosed):
                continue
            except ConnectionError:
                self._fail_over(rep, "channel dead mid-migration")
                continue
            except MXNetError:
                continue
            fr._point_at(req, rep.id)
            self.flight.hop(fr.id, rep.id)
            self.flight.event(
                fr.id, "migrated", hop=fr._hop,
                reason=self._place_reason(rep, fr),
                **{"from": fr._detached_from, "to": rep.id,
                   "resume_len": len(fr._rec["tokens"])})
            return True
        return False

    # -- fleet tracing / SLO decomposition ------------------------------
    def _absorb_hop(self, fr, rep):
        """Copy one engine's flight records for this request into the
        stitched journey (idempotent — see
        :meth:`FleetFlightRecorder.absorb`)."""
        if not self.flight.enabled:
            return
        try:
            recs = rep.engine.flight.records(fr.id)
        except Exception:   # noqa: BLE001 — tracing never breaks serving
            return
        if recs:
            self.flight.absorb(fr.id, rep.id, recs)

    def _absorb_live(self, rid):
        """Lazy sweep backing a live ``timeline()`` query: fold in
        whatever the request's CURRENT replica has recorded so far."""
        fr = self._requests.get(rid)
        if fr is None or fr._replica_id is None:
            return
        rep = self._replicas.get(fr._replica_id)
        if rep is not None:
            self._absorb_hop(fr, rep)

    def _breakdown(self, fr, t_end):
        """The end-to-end SLO decomposition, phases-sum-to-wall style
        (PR 13): ``router_queue`` and ``prefill`` are exact
        sub-intervals of the TTFT window (they sum to fleet TTFT by
        construction), ``handoff_wait``/``decode_admission`` split the
        wire crossing, and ``decode`` is the remainder — so the five
        components sum to the measured end-to-end wall time exactly,
        failover gaps and all."""
        e2e = (t_end - fr._t_submit) * 1e3
        comp = dict.fromkeys(_SLO_COMPONENTS, 0.0)
        t_first = fr.t_first
        if fr._t_place is not None:
            comp["router_queue"] = (fr._t_place - fr._t_submit) * 1e3
            if t_first is not None:
                comp["prefill"] = (t_first - fr._t_place) * 1e3
        if fr._t_ready is not None and fr._t_deliver is not None:
            admit = fr._admit_ms or 0.0
            wait = (fr._t_deliver - fr._t_ready) * 1e3
            comp["decode_admission"] = min(admit, wait)
            comp["handoff_wait"] = max(0.0, wait - admit)
        comp["decode"] = max(0.0, e2e - sum(comp.values()))
        return e2e, comp

    def _observe(self, fr):
        """Once-per-request fleet SLO accounting, run every step and
        at close: observe fleet TTFT the first time a first token is
        visible, and on completion observe cadence, absorb the final
        hop's flight record, and retire the stitched journey with the
        decomposition in its meta."""
        t_first = fr.t_first
        if fr._ttft_seen is None and t_first is not None:
            ttft = (t_first - fr._t_submit) * 1e3
            fr._ttft_seen = ttft
            _TM_FLEET_TTFT.observe(ttft)
            if self.slo_ttft_ms is not None:
                (_TM_FLEET_SLO_TTFT_OK if ttft <= self.slo_ttft_ms
                 else _TM_FLEET_SLO_TTFT_MISS).inc()
        if not fr.done or fr._finalized:
            return
        fr._finalized = True
        t_done = fr.t_done
        gen = len(fr.tokens) - fr.resumed
        cadence = None
        if t_first is not None and t_done is not None and gen > 1:
            cadence = (t_done - t_first) / (gen - 1) * 1e3
            _TM_FLEET_CADENCE.observe(cadence)
            if self.slo_cadence_ms is not None:
                (_TM_FLEET_SLO_CAD_OK
                 if cadence <= self.slo_cadence_ms
                 else _TM_FLEET_SLO_CAD_MISS).inc()
        if not self.flight.enabled:
            return
        rep = self._replicas.get(fr._replica_id) \
            if fr._replica_id is not None else None
        if rep is not None:
            self._absorb_hop(fr, rep)
        e2e, comp = self._breakdown(
            fr, t_done if t_done is not None else time.perf_counter())
        slo = {k: round(v, 3) for k, v in comp.items()}
        slo["e2e_ms"] = round(e2e, 3)
        if fr._ttft_seen is not None:
            slo["ttft_ms"] = round(fr._ttft_seen, 3)
        if cadence is not None:
            slo["cadence_ms"] = round(cadence, 3)
        self.flight.retire(fr.id, fr.retire_reason or "done",
                           tokens=len(fr.tokens),
                           migrations=fr.migrations, slo=slo)

    def _slo_tick(self, now=None):
        """Refresh the fleet multi-window burn gauges (rate-limited
        inside ``tele.SloWindow``) — the engine-side ``_slo_tick``
        mirrored at fleet scope. Called at the end of every
        :meth:`step` and by the exposition server per ``/fleet``
        scrape."""
        for kind, thr, hist, windows in (
                ("ttft", self.slo_ttft_ms, _TM_FLEET_TTFT,
                 _FLEET_SLO_TTFT_WINDOWS),
                ("cadence", self.slo_cadence_ms, _TM_FLEET_CADENCE,
                 _FLEET_SLO_CADENCE_WINDOWS)):
            if thr is None:
                continue
            w = self._slo_windows.get(kind)
            if w is None or w.threshold != float(thr):
                w = tele.SloWindow(
                    hist, thr, target=self.slo_target,
                    windows=[(s, g) for s, g in windows])
                self._slo_windows[kind] = w
            w.tick(now)

    def fleet_table(self):
        """The ``GET /fleet`` rollup: per-replica health (role,
        occupancy, queue — dead replicas abbreviated), router queue
        state, lifetime stats, handoff figures, the SLO thresholds
        with their current burn-gauge readings, and the flight ring
        occupancy."""
        tbl = self.health()
        tbl["stats"] = {k: int(v) for k, v in self.stats.items()}
        live, retired = self.flight.ids()
        tbl["flight"] = {"live": live, "retired": retired}
        slo = {"ttft_ms": self.slo_ttft_ms,
               "cadence_ms": self.slo_cadence_ms,
               "target": self.slo_target}
        for kind, windows in (("ttft", _FLEET_SLO_TTFT_WINDOWS),
                              ("cadence", _FLEET_SLO_CADENCE_WINDOWS)):
            slo[kind + "_burn"] = {
                g.name.rsplit("_", 1)[-1]: g.value
                for _, g in windows}
        tbl["slo"] = slo
        return tbl

    # -- the drive loop -------------------------------------------------
    def step(self):
        """One fleet scheduling round: heartbeat sweep, hold-queue
        re-placement, then one ``step()`` on every non-idle live
        replica. A replica whose step raises a non-engine error
        (process death — ``InjectedCrash`` in tests, deliberately not
        an ``MXNetError``) or a typed ``EngineStuck`` fails over; its
        requests continue on peers."""
        self._check_open()
        now = time.perf_counter()
        for rep in list(self._replicas.values()):
            if not rep.alive or rep.engine._closed:
                continue
            if now - rep.last_hb >= self.heartbeat_s:
                rep.last_hb = now
                self._heartbeat(rep)
        self._drain_held()
        for rep in list(self._replicas.values()):
            if not rep.alive or rep.engine._closed \
                    or rep.engine.idle:
                continue
            flt = _FLEET_FAULTS
            ctx = flt.fleet_step_context(rep.id) \
                if flt is not None else None
            try:
                with (ctx if ctx is not None
                      else contextlib.nullcontext()):
                    rep.engine.step()
            except EngineClosed:
                self._fail_over(rep, "closed underneath the router")
            except EngineStuck:
                self._fail_over(rep, "watchdog trip")
            except MXNetError:
                raise                      # a bug, not a death
            except Exception:              # InjectedCrash / SIGKILL
                self._fail_over(rep, "died mid-round")
        self._collect_handoffs()
        self._deliver_handoffs()
        # fleet SLO + journey finalization BEFORE the prune drops done
        # handles (attribute guards make the sweep a no-op per settled
        # request)
        for fr in list(self._requests.values()):
            if not fr._finalized:
                self._observe(fr)
        self._slo_tick(now)
        if self._requests and not self.stats["steps"] % 16:
            self._requests = {k: v for k, v in self._requests.items()
                              if not v.done}
        self.stats["steps"] += 1

    def serve_forever(self, requests=None):
        """Drive the fleet until idle, optionally ingesting submits
        from ``requests`` (same item protocol as the engine's
        ``serve_forever``: dict kwargs, ``(prompt, kwargs)``, a bare
        prompt, or ``None`` = nothing arrived yet). Returns every
        request retired during this call, submission order."""
        self._check_open()
        before = {rid for rid, fr in self._requests.items() if fr.done}
        it = iter(requests) if requests is not None else None
        while True:
            if it is not None:
                try:
                    item = next(it)
                except StopIteration:
                    it = None
                else:
                    if item is not None:
                        if isinstance(item, dict):
                            self.submit(**item)
                        elif isinstance(item, tuple) and len(item) == 2\
                                and isinstance(item[1], dict):
                            self.submit(item[0], **item[1])
                        else:
                            self.submit(item, max_tokens=16)
            if it is None and self.idle:
                break
            self.step()
        return [fr for rid, fr in self._requests.items()
                if fr.done and rid not in before]

    def cancel(self, request_id):
        """Retire one request wherever it lives (queued, in-flight on
        any replica, or held mid-migration); tokens so far stay
        readable. True if it was live."""
        fr = self._requests.get(request_id)
        if fr is None or fr.done:
            return False
        if fr._cur is not None \
                and fr._cur.retire_reason == "handoff":
            # in transit between replicas: mark cancelled here; the
            # next delivery pass sees ``done`` and resolves the
            # package (source slot freed, nothing admitted)
            fr._cancelled = True
            return True
        if fr._cur is not None:
            rep = self._replicas.get(fr._replica_id)
            if rep is not None and rep.alive \
                    and not rep.engine._closed:
                return rep.engine.cancel(request_id)
        try:
            self._held.remove(fr)
        except ValueError:
            pass
        fr._cancelled = True
        return True

    def close(self):
        """Shut the whole fleet down: every replica closes (its
        pending requests retire with ``EngineClosed``) and held
        requests fail the same way. Idempotent."""
        if self._closed:
            return
        self._closed = True
        for rep in self._replicas.values():
            with contextlib.suppress(Exception):
                rep.engine.close()
            rep.alive = False
        err = EngineClosed("FleetRouter was closed before this "
                           "request was re-placed")
        while self._held:
            fr = self._held.popleft()
            if not fr.done:
                fr._error = err
        while self._handoffs:
            pkg, fr = self._handoffs.popleft()
            with contextlib.suppress(Exception):
                pkg.resolve()
            if not fr.done:
                fr._error = err
        # settle the books: every journey retires (post-close replica
        # flight records are still readable — host-side rings)
        for fr in list(self._requests.values()):
            if not fr._finalized:
                self._observe(fr)
        _TM_LIVE.set(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
