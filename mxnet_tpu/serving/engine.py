"""Continuous-batching inference engine over a slot-paged KV cache.

Architecture (doc/serving.md has the full story):

* ONE persistent KV cache of ``S`` slots x ``max_len`` — ``Decoder``'s
  own cache layouts (plain float, int8-quantized scales, sliding-window
  rings) with the batch axis reinterpreted as a SLOT axis. A request
  occupies one slot from admission to retirement; a freed slot is
  recycled without touching the others (stale rows are hidden by the
  ``key_pos <= pos`` causal mask until overwritten; window rings get
  their position buffers reset at admission).

* FOUR compiled program families serve any request mix, ever (the
  fourth only with speculative decoding on; ``draft="model"`` adds
  the draft LM's proposal + prefill programs on top):

  - **bucketed prefill** (one program per power-of-2 length bucket):
    a prompt CHUNK padded to its bucket is pushed through the derived
    incremental graph at positions ``[start, start + C)`` of its
    assigned slot — slot index, start position, true chunk length,
    finality, temperature, rng key, eos id and token budget are all
    traced operands. The FINAL chunk samples the first output token
    in-program at the last real prompt position and scatter-updates
    the per-slot state vectors; non-final chunks (``prefill_chunk``
    pieces of a long prompt, interleaved with decode rounds —
    Sarathi-Serve, Agrawal et al. 2024) only write K/V and park the
    slot in a frozen state whose idempotent decode-round rewrite is
    harmless. Admission costs zero extra compiled programs.
  - **fused decode step** (exactly one program): one token for EVERY
    slot at its own position — per-slot position vector, per-slot
    temperature/rng sampling, vectorized EOS/length masking. Finished
    slots freeze (their write is idempotent) until reused.
  - **bucketed prefix copy** (one program per bucket, when the prefix
    cache is on): rows ``[0, B)`` of one cache slot land in another in
    a single compiled slice+scatter — pool→slot on a prefix hit
    (the matched prompt prefix's K/V replaces its prefill FLOPs,
    RadixAttention-style — Zheng et al. 2023), slot→pool when a
    freshly prefilled prompt is retained. Source/destination slot and
    direction are traced operands.
  - **speculative verify step** (exactly one program, ``draft`` on):
    the target model scores every slot's ``spec_k`` drafted tokens in
    one chunked dispatch and emits the accepted prefix plus one
    corrected token per slot — 1..``spec_k + 1`` tokens per weights
    read, byte-identical to plain decode by construction (drafts and
    their lengths are traced operands; doc/serving.md "Speculative
    decoding"; Leviathan et al. 2023, prompt-lookup drafting per the
    PLD/lookahead line).

* a host-side **prefix cache** (``serving/prefix.py``): a refcounted-
  LRU trie over token ids maps a new prompt to the longest prefix a
  RETAINED prompt shares with it; retained prompts own slots in a
  reserved on-device pool (same cache layout, extra slot axis rows)
  bounded by ``prefix_cache_mb``. Windowed-ring models bypass it —
  ring eviction invalidates absolute-position reuse (doc/serving.md).

* a host-side scheduler that admits queued requests into freed slots
  BETWEEN device steps (iteration-level / continuous batching — Orca,
  OSDI '22), retires finished sequences, and overlaps host work with
  device execution twice over: prompt h2d staging rides the unified
  depth-k ``io.StagedStream`` helper (PR 2's machinery), and output
  token vectors are drained ``drain_depth`` dispatches behind the
  device, so the step stream never blocks on either edge.

Determinism guarantees (pinned by tests/test_serving.py): greedy
(``temperature=0``) outputs are byte-identical to offline
``Decoder.generate`` per request, regardless of admission order, slot
assignment, co-resident requests, or bucket padding; sampled outputs
depend only on ``(seed, position)`` — not on scheduling.

Robustness (doc/serving.md "Serving under hostile traffic", all
host-side — the compiled program families above are the ONLY
device programs, frozen): per-request deadlines
(``deadline_ms``/``ttft_deadline_ms``) and :meth:`cancel` retire work
at round boundaries through the same dead-slot freeze + slot-recycle
machinery normal retirement uses; ``overload`` policies shed load with
a typed :class:`EngineOverloaded` instead of queueing unboundedly; a
round watchdog (``round_timeout_ms``) turns a wedged device dispatch
into a typed, recoverable :class:`EngineStuck`; per-request host
failures poison only their own request; :meth:`snapshot` /
:meth:`restore` rebuild the scheduler after a crash with
byte-identical continuations; :meth:`close` fails everything pending
with :class:`EngineClosed` and is idempotent.
"""
from __future__ import annotations

import collections
import itertools
import math
import os
import time
import warnings
import weakref

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .. import profiler
from .. import telemetry as tele
from ..io import StagedStream
from ..parallel.decode import Decoder
from .capture import CaptureStream
from .flight import FlightRecorder
from .handoff import HANDOFF_DTYPES, KVHandoff, unpack_rows
from .prefix import PrefixCache
from .spec import NgramDrafter

__all__ = ["InferenceEngine", "Request", "EngineOverloaded",
           "EngineClosed", "EngineStuck"]

# live engines in this process, for the observability plane only: the
# exposition server's /requests, /flight/<id> and /healthz walk this
# set (weak — an engine the caller dropped disappears with it)
_ENGINES = weakref.WeakSet()

# monotonic suffix for auto-assigned engine ids ("e<pid>.<n>"): the
# FleetRouter keys replicas by engine_id, and capture headers carry it
# as provenance, so ids must be unique within a process across
# engine rebuilds (a restore() successor gets a FRESH id; the donor's
# travels in ``migrated_from``)
_ENGINE_SEQ = itertools.count()

# serving-side fault injection (mxnet_tpu.testing.faults): an installed
# injector's hooks run at the engine's host-side seams — h2d/prefill
# admission work, post-dispatch (simulated crash), and the watchdog's
# readiness poll. None in production; never on a device path.
_SERVING_FAULTS = None


class EngineOverloaded(MXNetError):
    """Typed overload signal: raised by ``submit`` under the ``shed``
    policy when the queue is full (and attached as the ``error`` of
    requests evicted by ``shed_oldest``). Callers fail fast and retry
    against another replica instead of queueing into a missed SLO."""


class EngineClosed(MXNetError):
    """The engine was shut down: raised by ``submit``/``step`` after
    :meth:`InferenceEngine.close`, and attached as the ``error`` of
    requests that were still pending when close ran."""


class EngineStuck(MXNetError):
    """Round watchdog trip: a dispatched device round failed to
    materialize within ``round_timeout_ms``. The undrained round stays
    queued — a later ``step()`` retries it if the device recovers;
    otherwise ``snapshot()`` still works (host state only) and
    ``restore()`` resumes every request on a fresh engine."""

# hard bound on reserved prefix-pool slots: the byte budget is the
# real knob; this only stops a tiny model + big budget from minting a
# silly slot axis (256 entries is far past any workload's useful
# distinct-prefix count)
_MAX_POOL_SLOTS = 256

# per-request serving stats (doc/observability.md "serving"): all
# host-side perf_counter arithmetic on values the scheduler already
# tracks — nothing new crosses the device boundary
_TM_QUEUE_WAIT_MS = tele.histogram("serving.queue_wait_ms")
_TM_TTFT_MS = tele.histogram("serving.ttft_ms")
_TM_CADENCE_MS = tele.histogram("serving.token_cadence_ms")
_TM_TOKENS = tele.counter("serving.tokens")
_TM_COMPLETED = tele.counter("serving.completed")
_TM_RETIRED_EOS = tele.counter("serving.retired_eos")
_TM_RETIRED_LENGTH = tele.counter("serving.retired_length")
_TM_ROUNDS = tele.counter("serving.rounds")
_TM_PREFILLS = tele.counter("serving.prefills")
_TM_ADMITTED = tele.histogram(
    "serving.admitted_per_round", buckets=(0, 1, 2, 4, 8, 16, 32, 64))
_TM_SLOTS_BUSY = tele.histogram(
    "serving.slots_busy_per_round",
    buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128))
_TM_OCCUPANCY = tele.gauge("serving.slot_occupancy")
# info gauge: which attention impl the decode/verify programs trace —
# 1 = paged (Pallas live-row kernel), 0 = dense. Set at construction;
# with several engines in one process the gauge reflects the engine
# built last (the one-engine-per-process SLO note applies).
_TM_ATTN_IMPL = tele.gauge("serving.attn_impl")
# prefix cache + chunked prefill (all host-side: the lookup is a trie
# walk, the copy/chunk spans time dispatches — nothing crosses the
# device boundary beyond the programs themselves)
_TM_PREFIX_HITS = tele.counter("serving.prefix_hits")
_TM_PREFIX_MISSES = tele.counter("serving.prefix_misses")
_TM_PREFIX_HIT_TOKENS = tele.counter("serving.prefix_hit_tokens")
_TM_PREFIX_LOOKUP_MS = tele.histogram(
    "serving.prefix_lookup_ms",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0))
_TM_PREFIX_BYTES = tele.gauge("serving.prefix_cache_bytes")
_TM_PREFIX_EVICTIONS = tele.counter("serving.prefix_evictions")
_TM_PREFIX_INSERT_SKIPPED = tele.counter(
    "serving.prefix_insert_skipped")
_TM_CHUNKS = tele.histogram(
    "serving.prefill_chunks_per_request",
    buckets=(1, 2, 4, 8, 16, 32, 64))
# speculative decoding (doc/serving.md "Speculative decoding"): all
# host-side accounting on values the drain already sees — drafted vs
# accepted tokens, the per-slot accepted-length shape, drafter source
# mix, and rounds that fell back to the plain decode program
_TM_SPEC_ROUNDS = tele.counter("serving.spec_rounds")
_TM_SPEC_FALLBACK = tele.counter("serving.spec_fallback_rounds")
_TM_SPEC_DRAFTED = tele.counter("serving.spec_drafted_tokens")
_TM_SPEC_ACCEPTED = tele.counter("serving.spec_accepted_tokens")
_TM_SPEC_ACCEPT_LEN = tele.histogram(
    "serving.spec_accepted_per_step",
    buckets=(0, 1, 2, 3, 4, 6, 8, 12, 16))
_TM_SPEC_NGRAM = tele.counter("serving.spec_drafts_ngram")
_TM_SPEC_MODEL = tele.counter("serving.spec_drafts_model")
# tensor-parallel serving (doc/serving.md "Tensor-parallel serving"):
# info gauges set at construction — the sharding degree (1 = unsharded)
# and each shard's slice of the serving KV cache in bytes (the
# multi-chip win condition: decode is memory-bound, so bytes/shard is
# what scales down with chips). Engine-last-built semantics like
# serving.attn_impl.
_TM_TP = tele.gauge("serving.tp_degree")
_TM_TP_KV_BYTES = tele.gauge("serving.kv_bytes_per_shard")
# weight-only quantization (doc/serving.md "Quantized weights"): info
# gauges set at construction — the weight storage dtype (0 = float,
# 1 = int8) and the engine's total stored weight bytes (quantized
# entries count int8 values + scales; the draft model's weights, when
# present, are included — they ride the same programs). Engine-last-
# built semantics like serving.attn_impl.
_TM_WEIGHT_DTYPE = tele.gauge("serving.weight_dtype")
_TM_WEIGHT_BYTES = tele.gauge("serving.weight_bytes")
# fused quantized kernels (doc/serving.md "Fused quantized kernels"):
# info gauges set at construction — which matmul impl the quantized
# products trace (0 = dense fori loop, 1 = pallas, 2 = pallas + fused
# decode chain) and the int4 per-group scale width (0 = not int4 /
# auto). Engine-last-built semantics like serving.attn_impl.
_TM_MATMUL_IMPL = tele.gauge("serving.matmul_impl")
_TM_WEIGHT_GROUP = tele.gauge("serving.weight_group_size")
# disaggregated prefill/decode (doc/serving.md "Disaggregated
# prefill/decode"): info gauge for the engine's role (0 = unified,
# 1 = prefill, 2 = decode; engine-last-built semantics like
# serving.attn_impl) and the time a FINISHED prefill's package waited
# between export-ready and decode-side admission — the queueing cost
# the split adds in front of decode, observed by the router at
# delivery
_TM_ROLE = tele.gauge("serving.role")
_TM_HANDOFF_WAIT = tele.histogram("serving.handoff_wait_ms")
# compile_counts re-exported as telemetry: the in-engine log stays the
# tested contract; these make recompiles visible in ONE snapshot next
# to everything else
_TM_COMPILE_DECODE = tele.counter("serving.compiles_decode")
_TM_COMPILE_PREFILL = tele.counter("serving.compiles_prefill")
_TM_COMPILE_COPY = tele.counter("serving.compiles_copy")
_TM_COMPILE_VERIFY = tele.counter("serving.compiles_verify")
_TM_COMPILE_DRAFT = tele.counter("serving.compiles_draft")
_TM_COMPILE_HANDOFF = tele.counter("serving.compiles_handoff")
# robustness counters (doc/observability.md): every abnormal retirement
# path is visible in the same snapshot as the latencies it protects
_TM_SHED = tele.counter("serving.shed")
_TM_DEADLINE = tele.counter("serving.deadline_missed")
_TM_CANCELLED = tele.counter("serving.cancelled")
_TM_ERRORS = tele.counter("serving.request_errors")
_TM_WATCHDOG = tele.counter("serving.watchdog_trips")
_TM_RESTORES = tele.counter("serving.restores")
# SLO accounting (doc/observability.md "SLO accounting"): attainment
# counters tick at the same host-side points that feed the TTFT and
# cadence histograms; the burn gauges are multi-window derivatives of
# those histograms (tele.SloWindow), refreshed each round and on every
# exposition-server scrape. Declared with literal names so the metric
# catalog lint sees them.
_TM_SLO_TTFT_OK = tele.counter("serving.slo_ttft_attained")
_TM_SLO_TTFT_MISS = tele.counter("serving.slo_ttft_missed")
_TM_SLO_CAD_OK = tele.counter("serving.slo_cadence_attained")
_TM_SLO_CAD_MISS = tele.counter("serving.slo_cadence_missed")
_SLO_TTFT_WINDOWS = (
    (60.0, tele.gauge("serving.slo_ttft_burn_1m")),
    (300.0, tele.gauge("serving.slo_ttft_burn_5m")),
    (3600.0, tele.gauge("serving.slo_ttft_burn_1h")))
_SLO_CADENCE_WINDOWS = (
    (60.0, tele.gauge("serving.slo_cadence_burn_1m")),
    (300.0, tele.gauge("serving.slo_cadence_burn_5m")),
    (3600.0, tele.gauge("serving.slo_cadence_burn_1h")))
# round-phase attribution (doc/observability.md "Round-phase
# attribution"): where one step()'s wall time went. Every phase is a
# same-thread perf_counter interval the step already brackets; "sched"
# is the unattributed remainder (host scheduling — sweeps, queue
# bookkeeping, chunk math), so the phases SUM to the round wall time
# by construction. Sub-ms buckets: decode rounds are ms-scale.
_PHASE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                  25.0, 50.0, 100.0, 500.0, 5000.0)
_TM_PHASE = {
    "sched": tele.histogram("serving.round_phase_ms.sched",
                            buckets=_PHASE_BUCKETS),
    "prefix_lookup": tele.histogram(
        "serving.round_phase_ms.prefix_lookup",
        buckets=_PHASE_BUCKETS),
    "h2d": tele.histogram("serving.round_phase_ms.h2d",
                          buckets=_PHASE_BUCKETS),
    "prefill": tele.histogram("serving.round_phase_ms.prefill",
                              buckets=_PHASE_BUCKETS),
    "copy": tele.histogram("serving.round_phase_ms.copy",
                           buckets=_PHASE_BUCKETS),
    "dispatch": tele.histogram("serving.round_phase_ms.dispatch",
                               buckets=_PHASE_BUCKETS),
    "drain": tele.histogram("serving.round_phase_ms.drain",
                            buckets=_PHASE_BUCKETS),
}
_TM_ROUND_WALL = tele.histogram("serving.round_wall_ms",
                                buckets=_PHASE_BUCKETS)
# bounded per-engine ledger of recent rounds (GET /rounds); the
# histograms above are the fleet view, the ledger is the incident view
_ROUND_LEDGER = 256


class Request:
    """One submitted generation request (handle returned by
    :meth:`InferenceEngine.submit`).

    ``tokens`` fills in as output drains: generated ids only (no
    prompt echo), including ``eos_id`` when hit. ``done`` flips when
    the sequence retires; ``result()`` returns the tokens as int32
    numpy. Latency probes: ``t_submit``/``t_admit``/``t_first``/
    ``t_done`` (perf_counter seconds; admit = slot assigned + prefill
    dispatched; first = first token DRAINED, i.e. visible to the
    caller, not merely computed). ``retire_reason`` once done is
    ``"eos"`` / ``"length"`` (normal completion), ``"deadline"`` /
    ``"cancelled"`` (host-retired, ``result()`` returns the tokens
    generated so far), or ``"shed"`` / ``"error"`` / ``"closed"``
    (failed — ``result()`` raises the typed ``error``; partial tokens
    stay readable on ``.tokens``). ``prefix_hit_tokens`` counts prompt
    positions whose K/V came from the prefix cache instead of prefill
    FLOPs; ``prefill_chunks`` how many prefill dispatches admitted the
    prompt (1 unless ``prefill_chunk`` split it). The same breakdown
    feeds the ``serving.*`` telemetry histograms (queue wait / TTFT /
    per-token cadence / prefix + chunk stats — doc/observability.md).
    """

    def __init__(self, rid, prompt, max_tokens, eos_id, temperature,
                 seed, limit, deadline_ms=None, ttft_deadline_ms=None,
                 resume_tokens=()):
        self.id = rid
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.eos_id = eos_id
        self.temperature = temperature
        self.seed = seed
        self.limit = limit          # min(max_tokens, max_len - P)
        # tokens already emitted by a pre-crash engine (restore());
        # ``seq`` is what admission prefills — re-prefilling the
        # emitted suffix puts every position's draw key back where the
        # uninterrupted run had it (byte-identical continuations)
        self.tokens = list(int(t) for t in resume_tokens)
        self.resumed = len(self.tokens)
        self.seq = prompt if not self.resumed else np.concatenate(
            [prompt, np.asarray(self.tokens, np.int32)])
        self.done = False
        self.error = None
        self.t_submit = time.perf_counter()
        self.t_admit = None
        self.t_first = None
        self.t_done = None
        self.retire_reason = None
        self.prefix_hit_tokens = 0
        self.prefill_chunks = 0
        self.deadline_ms = deadline_ms
        self.ttft_deadline_ms = ttft_deadline_ms
        # fleet trace context: (trace_id, hop) when a FleetRouter
        # minted this request's identity, None for direct submits
        self.trace = None
        self._deadline = None if deadline_ms is None \
            else self.t_submit + deadline_ms / 1e3
        self._ttft_deadline = None if ttft_deadline_ms is None \
            else self.t_submit + ttft_deadline_ms / 1e3
        self._cancelled = False

    def _expired(self, now):
        """Which deadline (if any) has passed — checked at round
        boundaries and at admission pop (host clock only)."""
        if self._deadline is not None and now >= self._deadline:
            return True
        return self._ttft_deadline is not None and self.t_first is None \
            and now >= self._ttft_deadline

    def result(self):
        if not self.done:
            raise MXNetError("request %s is not finished" % self.id)
        if self.error is not None:
            raise self.error
        return np.asarray(self.tokens, np.int32)

    def __repr__(self):
        return ("Request(id=%r, prompt_len=%d, max_tokens=%d, done=%s, "
                "generated=%d)" % (self.id, len(self.prompt),
                                   self.max_tokens, self.done,
                                   len(self.tokens)))


class _PlacementError:
    """Marker riding a staged ``(req, dev)`` tuple when
    ``_place_prompt`` failed: admission retires the request with the
    carried error instead of serving it."""

    def __init__(self, error):
        self.error = error


class _PendingSource:
    """StagedStream source over the engine's pending deque (empty deque
    = StopIteration; the stream runs ``live_source`` mode, so submits
    arriving later are staged by the very next fill)."""

    def __init__(self, dq):
        self._dq = dq

    def next(self):
        if not self._dq:
            raise StopIteration
        return self._dq.popleft()

    def reset(self):
        pass


def _default_buckets(max_len):
    out, b = [], 16
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


def _raw_key(seed):
    """threefry PRNGKey layout without dispatching a device op (the
    compile-count contract stays clean): [hi32, lo32] of the seed."""
    seed = int(seed) & 0xFFFFFFFFFFFFFFFF
    return np.array([seed >> 32, seed & 0xFFFFFFFF], np.uint32)


class InferenceEngine:
    """Continuous-batching serving loop over a :class:`Decoder`.

    Parameters
    ----------
    decoder : Decoder
        The derived incremental program (any cache flavor: bf16/int8
        ``cache_dtype``, sliding-window models, GQA, rope). Build one
        with ``Decoder(symbol, params, max_len=...)`` or use
        :meth:`from_checkpoint` / ``FeedForward.as_serving_engine``.
        ``cache_block`` prefix-bounded reads are not supported under
        slot addressing (each slot has its own clock) — construct the
        decoder with ``cache_block=None`` (the engine refuses
        otherwise rather than silently decoding differently).
    slots : int
        ``S``, the resident-sequence capacity — the continuous batch
        size and the cache's slot-axis length. Throughput knob: decode
        cost per step is roughly flat until the chip saturates, so
        more slots = more tokens per step (tools/bench_serving.py
        sweeps it).
    prefill_buckets : tuple of int, optional
        Ascending prompt-padding lengths; a prompt takes the smallest
        bucket >= its length (default: powers of two from 16, capped
        at ``max_len``). One prefill program compiles per bucket
        actually used — the whole compile budget is
        ``len(buckets) + 1``.
    max_queue : int
        Backpressure bound on submitted-but-not-admitted requests;
        ``submit`` raises ``MXNetError`` beyond it.
    stage_depth : int
        Depth of the prompt h2d stager (``io.StagedStream``).
    drain_depth : int
        How many step outputs may remain un-drained while work is in
        flight — the d2h analogue of ``stage_depth``. Retirement is
        discovered at drain time, so a slot frees at most
        ``drain_depth`` rounds after its sequence finished (the device
        freezes finished slots in the meantime).
    steps_per_round : int
        Tokens decoded per dispatched round: the decode program is a
        ``lax.scan`` of this many fused all-slots steps, amortizing
        the per-dispatch host/relay overhead k-fold (one jit call,
        one [k, S] output drain per k tokens). Admission/retirement
        granularity coarsens to k tokens — a slot freed mid-round sits
        frozen until the round ends, so k should stay well under the
        typical output length (k=1 is latency-optimal per-token
        scheduling; the chip-facing bench uses 8). Still ONE compiled
        decode program either way.
    prefix_cache_mb : float, optional
        Byte budget (MiB) for the prefix-reuse pool: prompts are
        retained as on-device K/V rows in a reserved slot pool, and a
        new request whose prompt shares a prefix with a retained one
        gets that prefix COPIED into its slot (one compiled copy per
        bucket) instead of re-prefilled — shared system prompts stop
        paying their FLOPs per request. Default: the
        ``MXNET_SERVING_PREFIX_CACHE_MB`` env var, else 64. ``0``
        disables. Pool slots = budget // per-slot cache bytes (capped
        at 256); eviction is refcounted LRU. Windowed-ring decoders
        bypass the cache automatically (ring eviction invalidates
        absolute-position reuse — doc/serving.md). Greedy outputs stay
        byte-identical with the cache on or off.
    prefill_chunk : int, optional
        Chunked-prefill bound: a prompt (suffix) longer than this many
        tokens is admitted as a SEQUENCE of chunk-sized prefill
        dispatches interleaved with decode rounds, under a per-round
        prefill budget of one chunk shared by all in-flight admissions
        — resident decode slots stall ~one chunk of prefill work per
        round, not one whole prompt (nor a burst of them): the p99
        token-cadence lever under long-prompt traffic. Also lifts the
        submit length cap from the largest bucket to ``max_len - 1``
        (pieces only need the chunk to fit a bucket). Default: the
        ``MXNET_SERVING_PREFILL_CHUNK`` env var, else 0 (= monolithic
        prefill). Uses the SAME bucketed prefill programs (chunk start
        is a traced operand); greedy outputs stay byte-identical
        across any chunk boundary.
    overload : {"block", "shed", "shed_oldest"}, optional
        What a full queue does to ``submit`` (default: the
        ``MXNET_SERVING_OVERLOAD`` env var, else ``"block"``).
        ``block`` keeps the PR 3 backpressure contract (generic
        ``MXNetError``; callers drive ``step`` to drain). ``shed``
        fails the NEW request fast with a typed
        :class:`EngineOverloaded` — the router-facing policy: a
        rejected request can retry elsewhere instead of aging into a
        missed SLO. ``shed_oldest`` evicts the oldest QUEUED (never
        admitted) request instead — freshest-work-wins under bursts.
        Under either shedding policy the engine also degrades
        gracefully while the queue is full: admitted work keeps
        priority (the chunking queue always ran first) and
        prefix-cache RETENTION pauses, so slot-to-pool copy dispatches
        stop competing with serving work under pressure.
    round_timeout_ms : float, optional
        Round watchdog (default: ``MXNET_SERVING_ROUND_TIMEOUT_MS``
        env var, else 0 = off): when draining a dispatched round, the
        engine polls device-buffer readiness host-side and raises a
        typed :class:`EngineStuck` after this long instead of blocking
        ``serve_forever`` forever on a wedged dispatch. The undrained
        round stays queued — a later ``step()`` retries (transient
        stall), or ``snapshot()``/``restore()`` move the requests to a
        fresh engine (real wedge). Mutable attribute; size it well
        above the worst legitimate round (compiles excepted — first
        rounds trace).
    slo_ttft_ms / slo_cadence_ms : float, optional
        Per-engine SLO targets (defaults: ``MXNET_SERVING_SLO_TTFT_MS``
        / ``MXNET_SERVING_SLO_CADENCE_MS`` env vars, else unset = no
        SLO accounting): a request whose time-to-first-token (resp.
        steady per-token cadence) beats the target ticks
        ``serving.slo_*_attained``, otherwise ``_missed``; multi-window
        burn-rate gauges (``serving.slo_*_burn_{1m,5m,1h}``) are
        derived from the existing latency histograms each round and on
        every ``/metrics`` scrape. Measurement only — nothing here
        changes scheduling (that is ROADMAP item 5's job). Mutable
        attributes. ``slo_target`` (default 0.99) is the attainment
        objective the burn rates are normalized against.
    spec_k : int, optional
        Draft length for speculative decoding (default: the
        ``MXNET_SERVING_SPEC_K`` env var, else 4; only meaningful with
        ``draft != "off"``). Each verify round the target model scores
        up to ``spec_k`` drafted tokens per slot in ONE chunked
        dispatch and emits the accepted prefix plus one corrected
        token — up to ``spec_k + 1`` tokens per weights read instead
        of 1. Raising it helps only while drafts keep getting
        accepted; rejected positions are wasted chunk width.
    draft : {"off", "ngram", "model"}, optional
        Drafting source (default: the ``MXNET_SERVING_DRAFT`` env var,
        else ``"off"``). ``"ngram"`` is the host-side prompt-lookup
        drafter (:class:`~mxnet_tpu.serving.NgramDrafter` — no second
        model: propose the continuation that followed the current
        suffix earlier in the request's own prompt + output).
        ``"model"`` drafts with a small draft LM (pass
        ``draft_decoder``) sharing the slot-paged layout — one greedy
        k-token proposal program plus its own per-bucket prefill.
        Greedy outputs are byte-identical to ``draft="off"`` either
        way (the target verifies every token in-program); sampled
        requests accept a draft token only when it matches the
        target's own ``fold_in(seed, position)`` draw, so the sampled
        identity is preserved too (acceptance just gets rarer at hot
        temperatures). Windowed-ring decoders refuse speculation
        loudly (a ``UserWarning``; the chunk write would wrap rejected
        drafts onto live ring rows — same bypass precedent as the
        prefix cache) and serve with ``draft="off"``.
    draft_decoder : Decoder, optional
        The draft model for ``draft="model"`` (e.g. the 124M config
        drafting for a 350M target, loaded from its own checkpoint —
        ``from_checkpoint(draft_prefix=..., draft_epoch=...)`` builds
        it for you). Must share ``max_len``, be non-windowed, and use
        ``cache_block=None``; its vocabulary must cover the target's
        token ids.
    flight_recorder : int, optional
        How many RETIRED requests keep their full flight-recorder
        timeline (submit → staged → admitted → prefix hit/copy →
        prefill chunks → sampled decode progress → retire reason) for
        post-hoc reconstruction via ``engine.flight.timeline(id)`` or
        ``GET /flight/<id>``. Default: the
        ``MXNET_SERVING_FLIGHT_RECORDER`` env var, else 256; 0
        disables recording. Host-side, bounded (doc/observability.md
        "The flight recorder").
    attn_impl : {"dense", "paged"}, optional
        Cache-read strategy for the decode / verify / draft programs
        (default: the decoder's own ``attn_impl``, itself defaulted
        from ``MXNET_SERVING_ATTN_IMPL``, else ``"dense"``).
        ``"paged"`` traces them over the Pallas paged-attention kernel
        (``ops.pallas_kernels.paged_attention``): each slot's read
        walks only its LIVE cache rows — bounded by the per-slot
        position vector — with in-kernel int8 dequantization, cutting
        the per-token HBM traffic that dominates decode (the cache is
        read once at its stored width instead of gathered, and for
        int8 dequantized to a full float copy, whole every step).
        Greedy outputs stay byte-identical to ``"dense"`` in float
        flavors (online softmax is a reassociation); int8 carries the
        usual quantized-cache tolerance. The compile-count contract is
        unchanged — same program families, different kernels inside.
        Windowed-ring decoders warn and serve dense (ring rows live at
        wrapped positions); prefill keeps the dense bucketed programs
        (compute-bound, traced start). ``snapshot()``/``restore()``
        carry the knob. doc/serving.md "Paged attention".
    capture_dir : str, optional
        Traffic capture (the serving time machine's record half —
        doc/observability.md): when set (default: the
        ``MXNET_SERVING_CAPTURE_DIR`` env var, else off), the engine
        appends a crash-safe JSONL record per accepted submit (arrival
        time, prompt, sampling identity, deadlines) and per retirement
        (emitted tokens, reason, TTFT/cadence) to its own
        ``mx_capture_<pid>_<n>.jsonl`` in this directory, size-bounded
        by ``MXNET_SERVING_CAPTURE_MB`` (default 64; ``capture_mb``
        overrides). ``tools/replay_serving.py`` replays a capture
        byte-identically on a fresh engine — any config change can be
        validated offline against yesterday's traffic
        (``--verify``). Flushed per record: a killed process leaves a
        readable log. ``snapshot()`` carries the knob, so capture
        continues across a crash cycle (fresh file, same directory).
    tp : int, optional
        Tensor-parallel degree (default: the ``MXNET_SERVING_TP`` env
        var, else 1 = unsharded): the slot-paged KV cache — int8
        scales and draft-model caches included — is sharded over a
        ``tp``-device mesh's ``model`` axis on the KV-HEAD dimension,
        and every compiled program family (decode, bucketed prefill,
        per-bucket copy, verify, draft, draft_prefill) runs as ONE
        shard_map program: each device computes its heads' attention
        against its cache shard and everything else replicated at
        tp=1's exact shapes, with one all-gather per attention node
        as the only collective. One engine serves a model whose KV
        footprint exceeds a chip, and decode's per-shard cache
        traffic drops ~1/tp (doc/serving.md "Tensor-parallel
        serving"). Greedy outputs are byte-identical to tp=1 across
        the whole feature gauntlet (logits land replicated, so
        host-side sampling identity is untouched); the compile-count
        contract is unchanged. Every attention node's kv heads must
        divide ``tp`` evenly (GQA groups stay whole per shard —
        refused loudly otherwise). ``attn_impl="paged"`` composes:
        each shard runs the Pallas kernel against its local cache
        shard (a per-shard kv-head grid), so the live-rows cut and
        the per-shard cut multiply. ``snapshot()``/``restore()``
        carry the degree.
    mesh : jax.sharding.Mesh, optional
        Serve over an existing mesh instead of building one: must
        carry a ``model`` axis (its size is the tp degree;
        ``parallel.model_parallel_mesh`` builds the canonical
        single-axis one). Mutually consistent with ``tp`` when both
        are given.
    weight_dtype : {"float", "int8"}, optional
        Weight storage for the engine's programs (default: the
        decoder's own ``weight_dtype``, itself defaulted from
        ``MXNET_SERVING_WEIGHT_DTYPE``, else ``"float"``). ``"int8"``
        quantizes the engine's OWN copy of every matmul weight —
        attention QKV/out projections, the MLP and unembedding
        FullyConnecteds, Embedding tables, MoE gate/expert stacks,
        and the draft model's weights when ``draft="model"`` — to
        int8 with per-output-channel f32 scales (LayerNorm and biases
        stay float), and every compiled program family dequantizes ON
        THE FLY inside a chunked scale-fused matmul (no float weight
        copy is ever materialized), so decode reads the weight stream
        at 1 byte/elem — the serving-batch bytes/token lever, and
        more resident slots per HBM byte. The decoder object stays
        float, so one weight set serves a quantized engine next to
        its fp oracle. Greedy outputs are argmax-stable vs. the fp
        engine on the tested configs (tolerance-bounded in general —
        the int8-KV contract); quantized engines stay byte-identical
        ACROSS their own gauntlet (tp degrees, admission orders,
        speculation, snapshot/restore). Composes with everything:
        tp>1 (scales replicate with their weights), int8 KV, paged
        attention, prefix cache, chunked prefill, both speculation
        modes, capture/replay. ``snapshot()``/``restore()`` and the
        capture header carry the knob. doc/serving.md "Quantized
        weights".
    """

    def __init__(self, decoder, slots=8, prefill_buckets=None,
                 max_queue=256, stage_depth=2, drain_depth=2,
                 steps_per_round=1, prefix_cache_mb=None,
                 prefill_chunk=None, overload=None,
                 round_timeout_ms=None, slo_ttft_ms=None,
                 slo_cadence_ms=None, slo_target=0.99,
                 flight_recorder=None, spec_k=None, draft=None,
                 draft_decoder=None, attn_impl=None, capture_dir=None,
                 capture_mb=None, tp=None, mesh=None,
                 weight_dtype=None, weight_group=None, matmul_impl=None,
                 ep=None, engine_id=None, migrated_from=None,
                 role=None, handoff_dtype=None):
        if not isinstance(decoder, Decoder):
            raise MXNetError("InferenceEngine needs a Decoder, got %r"
                             % type(decoder).__name__)
        if decoder._cache_block is not None:
            raise MXNetError(
                "InferenceEngine: slot-paged decoding does not support "
                "cache_block prefix-bounded reads (per-slot positions); "
                "build the Decoder with cache_block=None")
        self._dec = decoder
        self._t0 = time.perf_counter()   # ledger/capture time origin
        # fleet identity: engine_id names this replica (FleetRouter
        # rotation key, capture-header provenance); migrated_from is
        # the donor's id when this engine was built by restore() from
        # another engine's snapshot — requests it finishes attribute
        # to the replica lineage that served them
        self.engine_id = str(engine_id) if engine_id is not None \
            else "e%d.%d" % (os.getpid(), next(_ENGINE_SEQ))
        self.migrated_from = None if migrated_from is None \
            else str(migrated_from)
        # drain state: set by FleetRouter.drain (or an operator)
        # before migration — admission stops routing here and
        # /healthz reports it, distinct from stuck/closed
        self.draining = False
        self.max_len = decoder.max_len
        self.slots = int(slots)
        if self.slots < 1:
            raise MXNetError("InferenceEngine: slots must be >= 1")
        if prefill_buckets is None:
            prefill_buckets = _default_buckets(self.max_len)
        buckets = tuple(int(b) for b in prefill_buckets)
        if not buckets or list(buckets) != sorted(set(buckets)) \
                or buckets[0] < 1 or buckets[-1] > self.max_len:
            raise MXNetError(
                "InferenceEngine: prefill_buckets must be strictly "
                "ascending lengths in [1, max_len], got %r" % (buckets,))
        self.prefill_buckets = buckets
        self.max_queue = int(max_queue)
        self._drain_depth = max(0, int(drain_depth))
        self.steps_per_round = int(steps_per_round)
        if self.steps_per_round < 1:
            raise MXNetError("InferenceEngine: steps_per_round must "
                             "be >= 1")
        if prefill_chunk is None:
            prefill_chunk = int(os.environ.get(
                "MXNET_SERVING_PREFILL_CHUNK", "0") or 0)
        self.prefill_chunk = int(prefill_chunk)
        if self.prefill_chunk < 0:
            raise MXNetError("InferenceEngine: prefill_chunk must be "
                             ">= 0 (0 disables chunking)")
        if self.prefill_chunk > buckets[-1]:
            raise MXNetError(
                "InferenceEngine: prefill_chunk=%d exceeds the largest "
                "prefill bucket %d — every chunk piece must fit a "
                "bucket program" % (self.prefill_chunk, buckets[-1]))
        if overload is None:
            overload = os.environ.get("MXNET_SERVING_OVERLOAD") \
                or "block"
        if overload not in ("block", "shed", "shed_oldest"):
            raise MXNetError(
                "InferenceEngine: overload must be 'block', 'shed' or "
                "'shed_oldest', got %r (MXNET_SERVING_OVERLOAD sets "
                "the default)" % (overload,))
        self.overload = overload
        if round_timeout_ms is None:
            round_timeout_ms = float(os.environ.get(
                "MXNET_SERVING_ROUND_TIMEOUT_MS") or "0")
        self.round_timeout_ms = float(round_timeout_ms)
        if self.round_timeout_ms < 0:
            raise MXNetError("InferenceEngine: round_timeout_ms must "
                             "be >= 0 (0 disables the watchdog)")
        if slo_ttft_ms is None:
            slo_ttft_ms = os.environ.get("MXNET_SERVING_SLO_TTFT_MS")
            slo_ttft_ms = float(slo_ttft_ms) if slo_ttft_ms else None
        if slo_cadence_ms is None:
            slo_cadence_ms = os.environ.get(
                "MXNET_SERVING_SLO_CADENCE_MS")
            slo_cadence_ms = float(slo_cadence_ms) if slo_cadence_ms \
                else None
        for nm, v in (("slo_ttft_ms", slo_ttft_ms),
                      ("slo_cadence_ms", slo_cadence_ms)):
            if v is not None and not v > 0:
                raise MXNetError("InferenceEngine: %s must be > 0 "
                                 "(None disables SLO accounting), got "
                                 "%r" % (nm, v))
        if not 0.0 < float(slo_target) < 1.0:
            raise MXNetError("InferenceEngine: slo_target must be in "
                             "(0, 1), got %r" % (slo_target,))
        self.slo_ttft_ms = slo_ttft_ms
        self.slo_cadence_ms = slo_cadence_ms
        self.slo_target = float(slo_target)
        self._slo_windows = {}
        if flight_recorder is None:
            flight_recorder = int(os.environ.get(
                "MXNET_SERVING_FLIGHT_RECORDER", "") or 256)
        if int(flight_recorder) < 0:
            raise MXNetError("InferenceEngine: flight_recorder must "
                             "be >= 0 (0 disables the recorder)")
        self.flight = FlightRecorder(retain=int(flight_recorder))
        self.stage_depth = int(stage_depth)

        # tensor-parallel serving (doc/serving.md "Tensor-parallel
        # serving"): resolve the mesh/degree FIRST — the cache layout,
        # the replicated parameter placement and every compiled
        # program's shard_map wrapper depend on it
        if mesh is None and tp is None:
            tp = int(os.environ.get("MXNET_SERVING_TP", "") or 1)
        if mesh is not None:
            if "model" not in mesh.axis_names:
                raise MXNetError(
                    "InferenceEngine: mesh=... needs a 'model' axis "
                    "to shard the KV cache over (axes: %r) — "
                    "parallel.model_parallel_mesh builds one"
                    % (mesh.axis_names,))
            if tp is not None and int(tp) != int(mesh.shape["model"]):
                raise MXNetError(
                    "InferenceEngine: tp=%r disagrees with the mesh's "
                    "model axis size %d — pass one or the other"
                    % (tp, mesh.shape["model"]))
            tp = int(mesh.shape["model"])
        tp = int(tp)
        if tp < 1:
            raise MXNetError("InferenceEngine: tp must be >= 1 "
                             "(1 = unsharded; MXNET_SERVING_TP sets "
                             "the default), got %d" % tp)
        # expert-parallel MoE (doc/serving.md "Expert-parallel MoE"):
        # an "expert" mesh axis composed with tp — the per-expert
        # weight stacks (the largest tensors in a MoE config) shard
        # on their leading expert axis instead of replicating per
        # shard; moe_ffn_math gathers gate logits / psums the combine
        if ep is None:
            ep = int(os.environ.get("MXNET_SERVING_EP", "") or 1)
        ep = int(ep)
        if ep < 1:
            raise MXNetError("InferenceEngine: ep must be >= 1 "
                             "(1 = no expert sharding; "
                             "MXNET_SERVING_EP sets the default), "
                             "got %d" % ep)
        moe_nodes = [n for n in decoder._topo
                     if not n.is_var and n.spec.name == "MoEFFN"]
        if ep > 1:
            if not moe_nodes:
                raise MXNetError(
                    "InferenceEngine: ep=%d needs a MoE decoder — no "
                    "MoEFFN node to shard experts over" % ep)
            for n in moe_nodes:
                nx = int(n.params["num_experts"])
                if nx % ep:
                    raise MXNetError(
                        "InferenceEngine: ep=%d must divide "
                        "num_experts=%d (node %r) — the expert stacks "
                        "shard their leading axis evenly"
                        % (ep, nx, n.name))
            if mesh is not None:
                if "expert" not in mesh.axis_names \
                        or int(mesh.shape["expert"]) != ep:
                    raise MXNetError(
                        "InferenceEngine: ep=%d disagrees with the "
                        "mesh's expert axis (axes: %r) — "
                        "parallel.build_mesh({'expert': ep, 'model': "
                        "tp}) builds a composed mesh"
                        % (ep, mesh.axis_names))
            else:
                from ..parallel.mesh import build_mesh
                mesh = build_mesh({"expert": ep, "model": tp})
        elif tp > 1 and mesh is None:
            from ..parallel.mesh import model_parallel_mesh
            mesh = model_parallel_mesh(tp)
        self.tp = tp
        self.ep = ep
        self._mesh = mesh if (tp > 1 or ep > 1) else None
        self._expert_names = set()
        if ep > 1:
            for n in moe_nodes:
                for inp, _ in n.inputs[1:]:
                    self._expert_names.add(inp.name)
        # weight-only quantization (doc/serving.md "Quantized
        # weights"): resolve BEFORE parameter placement — an int8
        # engine over a float decoder quantizes its OWN parameter
        # copy, so the decoder (and its offline oracle programs)
        # stays float and one weight set serves a quantized engine
        # next to its fp oracle (the identity tests do)
        if weight_dtype is None:
            weight_dtype = decoder.weight_dtype
        if weight_dtype not in ("float", "int8", "int4"):
            raise MXNetError(
                "InferenceEngine: weight_dtype must be 'float', "
                "'int8' or 'int4', got %r (MXNET_SERVING_WEIGHT_DTYPE "
                "sets the default)" % (weight_dtype,))
        if weight_dtype == "float" and decoder.weight_dtype != "float":
            raise MXNetError(
                "InferenceEngine: weight_dtype='float' over a Decoder "
                "built with weight_dtype='int8' — the float weights "
                "are gone; build the decoder float (the engine "
                "quantizes its own copy)")
        if decoder.weight_dtype != "float" \
                and weight_dtype != decoder.weight_dtype:
            raise MXNetError(
                "InferenceEngine: weight_dtype=%r over a Decoder "
                "already quantized to %r — re-flavoring quantized "
                "weights would re-round; build the decoder float (the "
                "engine quantizes its own copy)"
                % (weight_dtype, decoder.weight_dtype))
        self.weight_dtype = weight_dtype
        if weight_group is None:
            weight_group = decoder.weight_group
        self.weight_group = weight_group
        params, auxs = decoder._params, decoder._aux
        if weight_dtype != "float" and decoder.weight_dtype == "float":
            from .quant import quantize_params, quantized_weight_names
            params = quantize_params(
                params, quantized_weight_names(decoder._topo),
                bits=8 if weight_dtype == "int8" else 4,
                group=weight_group,
                row_quant=decoder._embedding_weight_names())
        if weight_dtype == "int4" and self.weight_group is None:
            # representative group for the gauges/geometry when the
            # engine quantized its own copy under the auto pick: read
            # it off a quantized matmul weight (the E-axis resolution
            # Decoder records when IT quantizes)
            from .quant import QuantizedTensor as _QT
            for v in params.values():
                if isinstance(v, _QT) and v.bits == 4:
                    self.weight_group = v.group
                    break
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            from ..ops.attention import MultiHeadAttention as _MHA
            # GQA head partitioning must divide evenly or refuse
            # loudly — an uneven split would give shards different
            # compute shapes and break the replicated-prefix
            # byte-identity argument
            for n in decoder._mha:
                _MHA.check_head_shards(n.params, tp)
            self._kv_shard = NamedSharding(
                self._mesh, PartitionSpec(None, None, "model"))
            rep = NamedSharding(self._mesh, PartitionSpec())
            self._rep_shard = rep
            # the engine's OWN parameter placement (see the
            # weight_dtype note above for why the decoder object is
            # never touched); QuantizedTensor entries are pytrees, so
            # device_put replicates their int8 values and scales alike.
            # Under ep>1 the MoE expert stacks shard their LEADING
            # expert axis instead of replicating — the whole point of
            # the expert mesh axis (quantized stacks shard values and
            # scales alike: both carry the expert axis first)
            exp = NamedSharding(self._mesh, PartitionSpec("expert")) \
                if ep > 1 else rep
            self._params = {
                k: jax.device_put(v, exp if k in self._expert_names
                                  else rep)
                for k, v in params.items()}
            self._aux = [jax.device_put(v, rep) for v in auxs]
        else:
            self._kv_shard = None
            self._rep_shard = None
            self._params, self._aux = params, auxs
        _TM_TP.set(tp)

        # device-resident: the slot-paged cache + per-slot state vectors
        S = self.slots
        self._caches = decoder.init_cache(S, kv_sharding=self._kv_shard)
        self._state = (
            jnp.zeros((S,), jnp.int32),        # pos: next write position
            jnp.zeros((S,), jnp.int32),        # tok: last sampled token
            jnp.zeros((S,), bool),             # live
            jnp.zeros((S,), jnp.float32),      # temperature
            jnp.zeros((S, 2), jnp.uint32),     # rng key
            jnp.full((S,), -1, jnp.int32),     # eos id (-1: none)
            jnp.zeros((S,), jnp.int32),        # last allowed position
        )
        if self._mesh is not None:
            self._state = tuple(jax.device_put(s, self._rep_shard)
                                for s in self._state)

        # prefix-reuse pool: a SEPARATE cache tree of pool slots (same
        # per-slot layout) holding retained prompt K/V. Separate, not
        # extra rows on the serving tree, so the fused decode step
        # keeps vmapping over exactly S lanes — pool size must never
        # tax the per-token path.
        if prefix_cache_mb is None:
            prefix_cache_mb = float(os.environ.get(
                "MXNET_SERVING_PREFIX_CACHE_MB") or "64")
        self.prefix_cache_mb = float(prefix_cache_mb)
        if self.prefix_cache_mb < 0:
            raise MXNetError("InferenceEngine: prefix_cache_mb must "
                             "be >= 0 (0 disables the prefix cache)")
        self._windowed = any(decoder._node_window(n)
                             for n in decoder._mha)
        # attention impl (doc/serving.md "Paged attention"): which
        # cache-read strategy the decode/verify/draft programs trace —
        # threaded into every Decoder._run_slots dispatch, so one
        # decoder can serve under either impl (the A/B bench and the
        # identity tests share weights across engines)
        if attn_impl is None:
            attn_impl = decoder._attn_impl
        if attn_impl not in ("dense", "paged"):
            raise MXNetError(
                "InferenceEngine: attn_impl must be 'dense' or "
                "'paged', got %r (MXNET_SERVING_ATTN_IMPL sets the "
                "default)" % (attn_impl,))
        if attn_impl == "dense" and decoder._attn_impl == "paged":
            raise MXNetError(
                "InferenceEngine: attn_impl='dense' over a Decoder "
                "built with attn_impl='paged' — build the decoder "
                "dense; the engine threads its own attn_impl into the "
                "slot programs")
        if attn_impl == "paged" and self._windowed:
            # refuse LOUDLY, then serve exactly (prefix-cache /
            # speculation precedent): ring rows live at wrapped
            # positions, outside the paged kernel's [0, pos) contract
            warnings.warn(
                "InferenceEngine: windowed-ring decoders do not "
                "compose with attn_impl='paged' (ring rows live at "
                "wrapped positions, not a [0, pos) prefix) — serving "
                "with the exact dense ring walk instead", UserWarning,
                stacklevel=2)
            attn_impl = "dense"
        # attn_impl="paged" composes with tp>1 since ISSUE 15: inside
        # the shard_map each device runs the Pallas kernel against its
        # LOCAL cache shard (the kernel's kv-head grid extent comes
        # from the cache operand, so it is per-shard automatically)
        # and the usual per-attention-node all-gather rebuilds the
        # head output — the PR 11 live-rows cut and the PR 14
        # per-shard cut multiply (doc/serving.md "Paged attention").
        self.attn_impl = attn_impl
        _TM_ATTN_IMPL.set(1 if attn_impl == "paged" else 0)
        # fused quantized kernels (doc/serving.md "Fused quantized
        # kernels"): which impl the quantized matmuls trace — threaded
        # into every Decoder._run_slots/_run dispatch like attn_impl.
        # "pallas" is bitwise-identical to "dense" (same output-
        # channel partition at the same resolve_chunk size); "fused"
        # additionally collapses each decode step's QKV→attention→
        # out-proj chain into one dispatch where eligible (paged,
        # c==1, tp=1, float KV) and falls back to the pallas product
        # elsewhere — token-stable, so it is its OWN knob value
        if matmul_impl is None:
            matmul_impl = decoder._matmul_impl
        if matmul_impl not in ("dense", "pallas", "fused"):
            raise MXNetError(
                "InferenceEngine: matmul_impl must be 'dense', "
                "'pallas' or 'fused', got %r (MXNET_SERVING_MATMUL_"
                "IMPL sets the default)" % (matmul_impl,))
        self.matmul_impl = matmul_impl
        # disaggregated prefill/decode (doc/serving.md "Disaggregated
        # prefill/decode"): role gates which program families ever
        # DISPATCH — a prefill engine runs admission + prefill only
        # and hands finished KV off; a decode engine admits handoffs
        # only and never traces a prefill program (a compile-memory
        # win the compile contract pins). Purely a scheduler gate: the
        # jit families are lazy, so nothing extra compiles either way.
        if role is None:
            role = os.environ.get("MXNET_SERVING_ROLE") or "unified"
        if role not in ("unified", "prefill", "decode"):
            raise MXNetError(
                "InferenceEngine: role must be 'unified', 'prefill' "
                "or 'decode', got %r (MXNET_SERVING_ROLE sets the "
                "default)" % (role,))
        if role != "unified" and self._windowed:
            raise MXNetError(
                "InferenceEngine: windowed-ring decoders do not "
                "compose with role=%r — ring rows live at wrapped "
                "positions, outside the [0, P) prefix contract the "
                "KV handoff rows ride (slot_prefix_rows); serve "
                "unified" % (role,))
        self.role = role
        if handoff_dtype is None:
            handoff_dtype = os.environ.get(
                "MXNET_SERVING_HANDOFF_DTYPE") or "native"
        if handoff_dtype not in HANDOFF_DTYPES:
            raise MXNetError(
                "InferenceEngine: handoff_dtype must be one of %s, "
                "got %r (MXNET_SERVING_HANDOFF_DTYPE sets the "
                "default)" % (", ".join(map(repr, HANDOFF_DTYPES)),
                              handoff_dtype))
        self.handoff_dtype = handoff_dtype
        _TM_ROLE.set({"unified": 0, "prefill": 1, "decode": 2}[role])
        slot_bytes = sum(x.nbytes for x in
                         jax.tree_util.tree_leaves(self._caches)) // S
        # per-shard KV residency (jax Array.nbytes is GLOBAL, so the
        # byte-budget semantics above are tp-invariant): the gauge the
        # tp sweep reads — what actually sits on each chip. Only
        # head-dim buffers (rank >= 3) shard; windowed rings'
        # position buffers replicate and reside in FULL on every
        # shard (Decoder.cache_specs is the layout source of truth)
        _TM_TP_KV_BYTES.set(sum(
            x.nbytes // self.tp if x.ndim >= 3 else x.nbytes
            for x in jax.tree_util.tree_leaves(self._caches)))
        pool_slots = 0
        if self.prefix_cache_mb > 0 and not self._windowed:
            pool_slots = min(
                int(self.prefix_cache_mb * 2**20) // max(1, slot_bytes),
                _MAX_POOL_SLOTS)
        if pool_slots > 0:
            self._pool = decoder.init_cache(pool_slots,
                                            kv_sharding=self._kv_shard)
            self._prefix = PrefixCache(pool_slots, slot_bytes)
        else:
            self._pool = None
            self._prefix = None

        # speculative decoding (doc/serving.md "Speculative decoding")
        if draft is None:
            draft = os.environ.get("MXNET_SERVING_DRAFT") or "off"
        if draft not in ("off", "ngram", "model"):
            raise MXNetError(
                "InferenceEngine: draft must be 'off', 'ngram' or "
                "'model', got %r (MXNET_SERVING_DRAFT sets the "
                "default)" % (draft,))
        if spec_k is None:
            spec_k = int(os.environ.get("MXNET_SERVING_SPEC_K", "")
                         or 4)
        self.spec_k = int(spec_k)
        if draft != "off":
            if self.spec_k < 1:
                raise MXNetError(
                    "InferenceEngine: spec_k must be >= 1 when draft "
                    "is on, got %d (MXNET_SERVING_SPEC_K sets the "
                    "default)" % self.spec_k)
            if self.spec_k > self.max_len - 3:
                raise MXNetError(
                    "InferenceEngine: spec_k=%d leaves no room in the "
                    "max_len=%d cache for a verify chunk (need "
                    "spec_k <= max_len - 3)"
                    % (self.spec_k, self.max_len))
            if self._windowed:
                # refuse LOUDLY, then serve unspeculated: the verify
                # chunk write would wrap rejected drafts onto live
                # ring rows (the prefix cache bypasses for the same
                # absolute-position reason)
                warnings.warn(
                    "InferenceEngine: windowed-ring decoders do not "
                    "compose with speculative decoding (the verify "
                    "chunk would wrap rejected drafts onto live ring "
                    "rows) — serving with draft='off'", UserWarning,
                    stacklevel=2)
                draft = "off"
        self.spec_draft = draft
        self._spec = draft != "off"
        self._drafters = {}           # request id -> NgramDrafter
        self._draft_dec = None
        if self.spec_draft == "model":
            if not isinstance(draft_decoder, Decoder):
                raise MXNetError(
                    "InferenceEngine: draft='model' needs a "
                    "draft_decoder (a Decoder over the small draft "
                    "LM), got %r" % type(draft_decoder).__name__)
            if draft_decoder.max_len != self.max_len:
                raise MXNetError(
                    "InferenceEngine: draft_decoder.max_len=%d must "
                    "equal the target's max_len=%d (the draft cache "
                    "mirrors the slot clocks)"
                    % (draft_decoder.max_len, self.max_len))
            if draft_decoder._cache_block is not None:
                raise MXNetError(
                    "InferenceEngine: draft_decoder must be built "
                    "with cache_block=None (slot addressing)")
            if any(draft_decoder._node_window(n)
                   for n in draft_decoder._mha):
                raise MXNetError(
                    "InferenceEngine: windowed draft models are not "
                    "supported (the catch-up chunk would wrap junk "
                    "onto live ring rows)")
            self._draft_dec = draft_decoder
            if self.weight_dtype == "float" \
                    and draft_decoder.weight_dtype == "int8":
                raise MXNetError(
                    "InferenceEngine: weight_dtype='float' over a "
                    "draft_decoder built with weight_dtype='int8' — "
                    "build the draft decoder float (the engine "
                    "quantizes its own copy)")
            dparams = draft_decoder._params
            if self.weight_dtype == "int8" \
                    and draft_decoder.weight_dtype != "int8":
                # the draft model reads its weights every proposal
                # round — quantize it with the target (engine copy,
                # same reasoning as above)
                from .quant import (quantize_params,
                                    quantized_weight_names)
                dparams = quantize_params(
                    dparams,
                    quantized_weight_names(draft_decoder._topo))
            if self._mesh is not None:
                from ..ops.attention import MultiHeadAttention as _MHA
                for n in draft_decoder._mha:
                    _MHA.check_head_shards(
                        n.params, self.tp,
                        where="tensor-parallel draft serving")
                self._draft_params = {
                    k: jax.device_put(v, self._rep_shard)
                    for k, v in dparams.items()}
                self._draft_aux = [jax.device_put(v, self._rep_shard)
                                   for v in draft_decoder._aux]
            else:
                self._draft_params = dparams
                self._draft_aux = draft_decoder._aux
            self._draft_caches = draft_decoder.init_cache(
                S, kv_sharding=self._kv_shard)
            self._draft_pos = [0] * S     # next draft-cache position
            self._draft_pending = [[] for _ in range(S)]

        # weight-storage info gauges (doc/observability.md): dtype +
        # the engine's total stored weight bytes — what int8 weights
        # buy is exactly this number shrinking while the programs
        # read it once per step (replicated per shard under tp)
        _TM_WEIGHT_DTYPE.set(
            {"float": 0, "int8": 1, "int4": 2}[self.weight_dtype])
        from .quant import weight_nbytes
        wbytes = weight_nbytes(self._params)
        if self._draft_dec is not None:
            wbytes += weight_nbytes(self._draft_params)
        self.weight_bytes = wbytes
        _TM_WEIGHT_BYTES.set(wbytes)
        _TM_MATMUL_IMPL.set(
            {"dense": 0, "pallas": 1, "fused": 2}[self.matmul_impl])
        _TM_WEIGHT_GROUP.set(int(self.weight_group or 0))

        # host-side scheduler state
        self._pending = collections.deque()
        self._stager = StagedStream(_PendingSource(self._pending),
                                    place=self._place_prompt,
                                    depth=stage_depth, live_source=True)
        self._free = collections.deque(range(S))  # FIFO slot recycling
        self._mirror = [None] * S   # drain-side view: slot -> Request
        self._drain = collections.deque()
        # requests admitted to a slot whose prompt is still being
        # chunk-prefilled, oldest first; plus one admission candidate
        # held over when a round's prefill budget ran out. Each round
        # runs at most ~prefill_chunk tokens of prefill work between
        # decode rounds (the chunked-prefill cadence bound)
        self._chunking = collections.deque()
        self._held = None
        self._round_budget = float("inf")
        self._next_id = 0
        self._auto_seed = 0
        # request lifecycle: every not-yet-done request, in submission
        # order (snapshot/restore replays this order); _watched is the
        # subset that can retire host-side (deadline or cancel) so the
        # per-round sweep never walks a deadline-less backlog
        self._active = {}            # id -> Request
        self._watched = set()        # ids with a deadline / cancel mark
        self._done_buf = []          # finished since the last step()
        self._closed = False
        # KV handoff state (role="prefill" exports, any non-prefill
        # role imports): _handoff_out holds packaged finished prefills
        # until the router resolves them; _handoff_slots are the cache
        # slots those packages pin (out of _free but carrying no live
        # request — idle/step accounting treats them as neither);
        # _imported is a bounded id ring for exactly-once admission
        # under retried deliveries
        self._handoff_out = collections.deque()
        self._handoff_slots = set()
        self._imported = collections.OrderedDict()
        self.stats = {"submitted": 0, "completed": 0, "prefills": 0,
                      "steps": 0, "tokens": 0, "prefix_hits": 0,
                      "prefix_hit_tokens": 0, "prefill_chunks": 0,
                      "prefix_copies": 0, "shed": 0, "deadline_missed": 0,
                      "cancelled": 0, "errors": 0, "watchdog_trips": 0,
                      "restores": 0, "spec_rounds": 0,
                      "spec_fallback_rounds": 0, "spec_drafted": 0,
                      "spec_accepted": 0, "handoffs_out": 0,
                      "handoffs_in": 0}

        # the compiled program families; the log records one tag
        # per TRACE (python side effects run at trace time only), so it
        # IS the compile count — tests pin the contract against it.
        # Under tp>1 every family body is wrapped in ONE shard_map
        # (_wrap_tp) before jit — same families, same counts, sharded
        # execution.
        self._compile_log = []
        self._tp_ax = ("model", self.tp) \
            if (self._mesh is not None and self.tp > 1) else None
        self._ep_ax = ("expert", self.ep) if self.ep > 1 else None
        # params in_spec: replicated, except the expert stacks under
        # ep>1 (leading-axis expert sharding — QuantizedTensor leaves
        # prefix-match the per-name spec)
        if self.ep > 1:
            from jax.sharding import PartitionSpec as _P
            self._param_spec = {
                k: (_P("expert") if k in self._expert_names else _P())
                for k in self._params}
        else:
            self._param_spec = "r"
        ps = self._param_spec
        on_chip = jax.default_backend() != "cpu"
        self._donate = (2, 3) if on_chip else ()
        self._copy_donate = (0, 1) if on_chip else ()
        cs = self._cache_spec(self._caches)
        self._step_fn = jax.jit(
            self._wrap_tp(self._make_step(),
                          (ps, "r", cs, "r"), (cs, "r", "r")),
            donate_argnums=self._donate)
        self._prefill_fns = {}
        self._copy_fns = {}
        self._handoff_fns = {}   # (bucket, write?) -> jitted row mover
        # speculative-decoding programs: ONE verify program (the whole
        # contract extension) plus, for draft="model", one draft
        # proposal program and a per-bucket draft prefill family
        self._verify_fn = None
        self._draft_fn = None
        self._draft_prefill_fns = {}
        if self._spec:
            self._verify_fn = jax.jit(
                self._wrap_tp(self._make_verify(),
                              (ps, "r", cs, "r", "r", "r"),
                              (cs, "r", "r")),
                donate_argnums=self._donate)
            if self.spec_draft == "model":
                dcs = self._cache_spec(self._draft_caches)
                self._draft_fn = jax.jit(
                    self._wrap_tp(self._make_draft(),
                                  ("r", "r", dcs, "r", "r", "r"),
                                  (dcs, "r")),
                    donate_argnums=(2,) if on_chip else ())
        # observability plane: watchdog/liveness state read by
        # health() and the exposition server's /healthz, plus the
        # once-per-program introspection registration guard
        self._last_ok_t = time.perf_counter()
        self._watchdog_stuck_t = None
        self._prog_seen = set()
        # round-phase attribution: _phase is the accumulator dict
        # while a step() is in flight (instrumented sites add their
        # same-thread perf_counter intervals), _rounds the bounded
        # ledger GET /rounds reads
        self._phase = None
        self._rounds = collections.deque(maxlen=_ROUND_LEDGER)
        self._round_no = 0
        # traffic capture: opened LAST so the header carries the final
        # geometry (windowed-ring fallbacks included); a disabled
        # stream (knob unset) is a no-op on every path
        self.capture = CaptureStream.open(
            capture_dir, capture_mb,
            dict(self._geometry(), max_len=self.max_len,
                 engine_id=self.engine_id,
                 migrated_from=self.migrated_from), self._t0)
        # resolved (env default included) so snapshot() carries it
        self.capture_dir = os.path.dirname(self.capture.path) \
            if self.capture.enabled else None
        _ENGINES.add(self)

    # -- construction ---------------------------------------------------
    @classmethod
    def from_checkpoint(cls, prefix, epoch, max_len, slots=8,
                        prefill_buckets=None, max_queue=256,
                        stage_depth=2, drain_depth=2, steps_per_round=1,
                        prefix_cache_mb=None, prefill_chunk=None,
                        overload=None, round_timeout_ms=None,
                        slo_ttft_ms=None, slo_cadence_ms=None,
                        slo_target=0.99, flight_recorder=None,
                        spec_k=None, draft=None, draft_decoder=None,
                        draft_prefix=None, draft_epoch=None,
                        attn_impl=None, capture_dir=None, tp=None,
                        mesh=None, weight_dtype=None,
                        **decoder_kwargs):
        """Checkpoint → serving engine in one call
        (``prefix-symbol.json`` + ``prefix-NNNN.params``, the reference
        format): builds the :class:`Decoder` via
        ``Decoder.from_checkpoint`` and wraps it. ``decoder_kwargs``
        reach the decoder (``compute_dtype``, ``cache_dtype``, ...).
        ``draft_prefix``/``draft_epoch`` load a SECOND (small)
        checkpoint as the speculative draft model — implies
        ``draft="model"`` unless overridden; the draft decoder
        inherits ``compute_dtype`` but none of the cache-flavor
        kwargs."""
        decoder_kwargs.setdefault("cache_block", None)
        # weight_dtype goes to the DECODER (which owns the env-default
        # resolution) and the engine inherits it: an explicit "float"
        # must be able to override MXNET_SERVING_WEIGHT_DTYPE=int8 —
        # an env-quantized decoder cannot serve a float engine (the
        # float weights are gone)
        decoder_kwargs.setdefault("weight_dtype", weight_dtype)
        dec = Decoder.from_checkpoint(prefix, epoch, max_len,
                                      **decoder_kwargs)
        if draft_prefix is not None and draft_decoder is None:
            draft_decoder = Decoder.from_checkpoint(
                draft_prefix, 0 if draft_epoch is None else draft_epoch,
                max_len, cache_block=None,
                compute_dtype=decoder_kwargs.get("compute_dtype"),
                weight_dtype=decoder_kwargs["weight_dtype"])
            if draft is None:
                draft = "model"
        return cls(dec, slots=slots, prefill_buckets=prefill_buckets,
                   max_queue=max_queue, stage_depth=stage_depth,
                   drain_depth=drain_depth,
                   steps_per_round=steps_per_round,
                   prefix_cache_mb=prefix_cache_mb,
                   prefill_chunk=prefill_chunk, overload=overload,
                   round_timeout_ms=round_timeout_ms,
                   slo_ttft_ms=slo_ttft_ms,
                   slo_cadence_ms=slo_cadence_ms, slo_target=slo_target,
                   flight_recorder=flight_recorder, spec_k=spec_k,
                   draft=draft, draft_decoder=draft_decoder,
                   attn_impl=attn_impl, capture_dir=capture_dir,
                   tp=tp, mesh=mesh)

    # -- compiled programs ----------------------------------------------
    def _cache_spec(self, tree):
        """Per-leaf PartitionSpec tree for a cache pytree under tp
        (None at tp=1) — Decoder.cache_specs, so the program specs and
        the cache layout can never drift."""
        if self._mesh is None:
            return None
        return Decoder.cache_specs(tree)

    def _wrap_tp(self, fn, in_specs, out_specs):
        """Tensor-parallel program wrapper (no-op at tp=1): shard_map
        ``fn`` over the mesh's model axis. ``"r"`` entries mean
        replicated (every device sees the full operand at tp=1's
        exact shape — the byte-identity lever); cache-spec trees mark
        the kv-head-sharded cache arguments. Inside, each device runs
        a plain single-device program on its cache shard; the ONLY
        collectives are the one-per-attention-node all-gathers
        ``Decoder._cached_mha`` inserts, so the program count and the
        trace-time compile log are exactly the tp=1 ones.
        ``check_rep=False``: replication of the replicated outputs is
        by construction (identical inputs, identical per-device
        programs), not something the rep-checker can see through the
        collectives."""
        if self._mesh is None:
            return fn
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec

        rep = PartitionSpec()

        def is_r(s):
            return isinstance(s, str) and s == "r"

        in_specs = tuple(rep if is_r(s) else s for s in in_specs)
        if is_r(out_specs):
            out_specs = rep
        elif isinstance(out_specs, tuple) \
                and not isinstance(out_specs, PartitionSpec):
            out_specs = tuple(rep if is_r(s) else s for s in out_specs)
        return shard_map(fn, mesh=self._mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)

    def _make_step(self):
        dec = self._dec
        k_rounds = self.steps_per_round
        impl = self.attn_impl
        mm = self.matmul_impl
        tp_ax = self._tp_ax
        ep_ax = self._ep_ax

        def one_step(caches, state, params, aux):
            pos, tok, live, temp, keys, eos, last = state
            # write each slot's pending token at ITS position, read
            # logits for the next one (frozen slots rewrite their last
            # token in place — idempotent)
            logits, caches = dec._run_slots(params, aux, caches, pos,
                                            tok[:, None], impl=impl,
                                            tp=tp_ax, mm_impl=mm,
                                            ep=ep_ax)
            logits = logits[:, 0]
            nxt_pos = pos + 1
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

            def with_sampling(_):
                t = jnp.where(temp > 0.0, temp, jnp.float32(1.0))

                def draw(k, q, row):
                    return jax.random.categorical(
                        jax.random.fold_in(k, q), row)

                sampled = jax.vmap(draw)(
                    keys, nxt_pos,
                    logits.astype(jnp.float32) / t[:, None]
                ).astype(jnp.int32)
                return jnp.where(temp > 0.0, sampled, greedy)

            # all-greedy rounds (the common case) must not pay the
            # per-slot fold_in + categorical they will never take —
            # same reasoning as Decoder._build_generate's lax.cond
            nxt = lax.cond(jnp.any(temp > 0.0), with_sampling,
                           lambda _: greedy, None)
            done_now = (nxt == eos) | (nxt_pos >= last)
            out = jnp.where(live, nxt, -1)     # -1: slot had no token
            live2 = live & ~done_now
            pos2 = jnp.where(live, nxt_pos, pos)
            tok2 = jnp.where(live, nxt, tok)
            return caches, (pos2, tok2, live2, temp, keys, eos, last), \
                out

        def step(params, aux, caches, state):
            # trace-time, see above; an introspection re-lower
            # (profiler.collect_program_stats on a lowering-cache
            # miss) must not count as a compile
            if not profiler.collecting():
                self._compile_log.append("decode")
                _TM_COMPILE_DECODE.inc()

            def body(carry, _):
                caches, st = carry
                caches, st, out = one_step(caches, st, params, aux)
                return (caches, st), out

            (caches, state), outs = lax.scan(body, (caches, state),
                                             None, length=k_rounds)
            return caches, state, outs          # outs [k, S]

        return step

    def _make_verify(self):
        """The ONE compiled verify program (doc/serving.md
        "Speculative decoding"): per round, the target model scores
        every slot's ``spec_k`` drafted tokens in one chunked run
        (``Decoder.verify_step_slots`` — the multi-token cache append
        plus in-program accepted-prefix computation) and emits the
        accepted prefix + one corrected token per slot. Slots with
        ``dlen == 0`` ride along and emit exactly their plain-decode
        token; rounds with NO drafts at all dispatch the plain decode
        program instead (the fallback path, counted)."""
        dec = self._dec
        impl = self.attn_impl
        mm = self.matmul_impl
        tp_ax = self._tp_ax
        ep_ax = self._ep_ax

        def verify(params, aux, caches, state, drafts, dlen):
            if not profiler.collecting():
                self._compile_log.append("verify")
                _TM_COMPILE_VERIFY.inc()
            return dec.verify_step_slots(params, aux, caches, state,
                                         drafts, dlen, impl=impl,
                                         tp=tp_ax, mm_impl=mm,
                                         ep=ep_ax)

        return verify

    def _make_draft(self):
        """The draft proposal program (``draft="model"``): catch the
        draft cache up on the tokens the target emitted since last
        round, then greedily propose ``spec_k`` tokens per slot
        (``Decoder.draft_propose_slots``)."""
        ddec = self._draft_dec
        k = self.spec_k
        impl = self.attn_impl
        mm = self.matmul_impl
        tp_ax = self._tp_ax

        def draft(params, aux, caches, pos, catchup, clen):
            if not profiler.collecting():
                self._compile_log.append("draft")
                _TM_COMPILE_DRAFT.inc()
            return ddec.draft_propose_slots(params, aux, caches, pos,
                                            catchup, clen, k,
                                            impl=impl, tp=tp_ax,
                                            mm_impl=mm)

        return draft

    def _draft_prefill_fn(self, bucket):
        """Per-bucket draft-cache prefill (``draft="model"``): write
        the prompt's K/V into the DRAFT model's slot cache — no
        sampling, no state vectors, just the cache build the proposal
        program decodes from. The draft model prefills the WHOLE
        prompt even on a prefix-cache hit (the pool holds target K/V
        only; the draft model is small enough that re-prefilling
        beats maintaining a second pool)."""
        if bucket not in self._draft_prefill_fns:
            ddec = self._draft_dec
            mm = self.matmul_impl
            tp_ax = self._tp_ax

            def dprefill(params, aux, caches, slot, tokens, start,
                         true_len):
                if not profiler.collecting():
                    self._compile_log.append(("draft_prefill", bucket))
                    _TM_COMPILE_DRAFT.inc()
                sub = ddec.slot_slice(caches, slot)
                sub = ddec.clear_window_positions(
                    sub, only_if=start == jnp.int32(0))
                _, sub = ddec._run(params, aux, sub, start, tokens,
                                   valid_len=start + true_len,
                                   tp=tp_ax, mm_impl=mm)
                return ddec.slot_update(caches, slot, sub)

            dcs = self._cache_spec(self._draft_caches)
            self._draft_prefill_fns[bucket] = jax.jit(
                self._wrap_tp(dprefill,
                              ("r", "r", dcs, "r", "r", "r", "r"),
                              dcs),
                donate_argnums=(2,) if self._donate else ())
        return self._draft_prefill_fns[bucket]

    def _prefill_fn(self, bucket):
        if bucket not in self._prefill_fns:
            dec = self._dec
            mm = self.matmul_impl
            tp_ax = self._tp_ax
            ep_ax = self._ep_ax

            def prefill(params, aux, caches, state, slot, tokens,
                        start, true_len, final, temp, key, eos,
                        max_toks):
                # ONE program per bucket serves whole prompts AND every
                # chunk of a chunked prefill: start, the chunk's true
                # length and finality are traced operands. total = the
                # absolute prompt length covered so far.
                if not profiler.collecting():
                    self._compile_log.append(("prefill", bucket))
                    _TM_COMPILE_PREFILL.inc()
                pos, tok, live, temps, keys, eoss, lasts = state
                total = start + true_len
                sub = dec.slot_slice(caches, slot)
                # ring-position reset: a recycled slot must not leak
                # the previous occupant's window entries — but ONLY on
                # the first chunk; later chunks extend the same ring
                sub = dec.clear_window_positions(
                    sub, only_if=start == jnp.int32(0))
                # valid_len (absolute): pad rows must not enter window
                # rings (they would EVICT real in-window keys — linear
                # cache rows are masked-until-overwritten, ring slots
                # wrap)
                logits, sub = dec._run(params, aux, sub, start, tokens,
                                       valid_len=total, tp=tp_ax,
                                       mm_impl=mm, ep=ep_ax)
                caches = dec.slot_update(caches, slot, sub)
                v = logits.shape[2]
                zero = jnp.int32(0)
                lastlog = lax.dynamic_slice(
                    logits, (zero, true_len - 1, zero), (1, 1, v))[0, 0]
                greedy = jnp.argmax(lastlog, -1).astype(jnp.int32)
                t = jnp.where(temp > 0.0, temp, jnp.float32(1.0))
                sampled = jax.random.categorical(
                    jax.random.fold_in(key, total),
                    lastlog.astype(jnp.float32) / t).astype(jnp.int32)
                t0 = jnp.where(temp > 0.0, sampled, greedy)
                lastp = jnp.minimum(total + max_toks - 1,
                                    dec.max_len - 1).astype(jnp.int32)
                done0 = (t0 == eos) | (total >= lastp)
                # a NON-final chunk parks the slot dead at (pos=total,
                # tok=last chunk token): the decode rounds that
                # interleave until the next chunk rewrite exactly that
                # token's K/V at row `total` — a row the next chunk
                # overwrites before any masked read could see it, the
                # same idempotent-freeze contract finished slots use
                lastchunk = lax.dynamic_slice(
                    tokens, (zero, true_len - 1), (1, 1))[0, 0]
                state2 = (pos.at[slot].set(total),
                          tok.at[slot].set(
                              jnp.where(final, t0, lastchunk)),
                          live.at[slot].set(final & ~done0),
                          temps.at[slot].set(temp),
                          keys.at[slot].set(key),
                          eoss.at[slot].set(eos),
                          lasts.at[slot].set(lastp))
                return caches, state2, t0

            cs = self._cache_spec(self._caches)
            self._prefill_fns[bucket] = jax.jit(
                self._wrap_tp(prefill,
                              (self._param_spec, "r", cs) + ("r",) * 10,
                              (cs, "r", "r")),
                donate_argnums=self._donate)
        return self._prefill_fns[bucket]

    def _copy_fn(self, bucket):
        """Compiled slot-to-slot prefix copy, one program per bucket:
        rows ``[0, bucket)`` of a source slot land in a destination
        slot. Source/destination may each be a serving slot or a pool
        slot — the direction booleans are traced operands, so ONE
        program covers pool→slot (prefix hit) and slot→pool
        (retention). int8 flavors copy their row scales alongside
        automatically (the copy is a tree-map over every cache
        buffer)."""
        if bucket not in self._copy_fns:
            def copy(serv, pool, src, dst, src_pool, dst_pool):
                if not profiler.collecting():
                    self._compile_log.append(("copy", bucket))
                    _TM_COMPILE_COPY.inc()
                rows = lax.cond(
                    src_pool,
                    lambda _: Decoder.slot_prefix_rows(pool, src,
                                                       bucket),
                    lambda _: Decoder.slot_prefix_rows(serv, src,
                                                       bucket),
                    None)
                serv = lax.cond(
                    dst_pool, lambda s: s,
                    lambda s: Decoder.slot_write_prefix_rows(s, dst,
                                                             rows),
                    serv)
                pool = lax.cond(
                    dst_pool,
                    lambda p: Decoder.slot_write_prefix_rows(p, dst,
                                                             rows),
                    lambda p: p, pool)
                return serv, pool

            self._copy_fns[bucket] = jax.jit(
                self._wrap_tp(copy,
                              (self._cache_spec(self._caches),
                               self._cache_spec(self._pool),
                               "r", "r", "r", "r"),
                              (self._cache_spec(self._caches),
                               self._cache_spec(self._pool))),
                donate_argnums=self._copy_donate)
        return self._copy_fns[bucket]

    def _dispatch_copy(self, length, src, dst, src_pool, dst_pool):
        """Bucket ``length`` and dispatch the copy program (prefix-hit
        admission or retention insert)."""
        bucket = self._bucket_for(length)
        tc0 = time.perf_counter()
        with tele.span("serving.prefix_copy", cat="serving",
                       bucket=bucket, to_pool=bool(dst_pool)):
            self._caches, self._pool = self._copy_fn(bucket)(
                self._caches, self._pool, np.int32(src), np.int32(dst),
                np.bool_(src_pool), np.bool_(dst_pool))
        self._phase_add("copy", time.perf_counter() - tc0)
        if ("copy", bucket) not in self._prog_seen:
            self._prog_seen.add(("copy", bucket))
            profiler.register_program(
                "serving_copy_b%d" % bucket, self._copy_fns[bucket],
                (self._caches, self._pool, np.int32(0), np.int32(0),
                 np.bool_(True), np.bool_(False)))
        self.stats["prefix_copies"] += 1

    # -- KV handoff (disaggregated prefill/decode) ----------------------
    def _handoff_fn(self, bucket, write=False):
        """Per-bucket handoff row movers, jitted lazily like the copy
        family: the EXPORT direction reads one slot's first ``bucket``
        KV rows out of the serving cache (``Decoder.slot_prefix_rows``
        — the same static-length/traced-slot contract the prefix pool
        copies ride), the IMPORT direction writes host rows into one
        slot (``slot_write_prefix_rows``, junk-row discipline
        unchanged: rows past the request's position are never read).
        Any one engine only ever fires ONE direction per bucket — a
        prefill engine exports, everyone else imports — so the
        ("handoff", bucket) compile tag stays once-per-bucket."""
        key = (bucket, bool(write))
        if key not in self._handoff_fns:
            cs = self._cache_spec(self._caches)
            if write:
                def run(serv, slot, rows, _b=bucket):
                    if not profiler.collecting():
                        self._compile_log.append(("handoff", _b))
                        _TM_COMPILE_HANDOFF.inc()
                    return Decoder.slot_write_prefix_rows(serv, slot,
                                                          rows)

                self._handoff_fns[key] = jax.jit(
                    self._wrap_tp(run, (cs, "r", cs), cs),
                    donate_argnums=(0,) if self._donate else ())
            else:
                def run(serv, slot, _b=bucket):
                    if not profiler.collecting():
                        self._compile_log.append(("handoff", _b))
                        _TM_COMPILE_HANDOFF.inc()
                    return Decoder.slot_prefix_rows(serv, slot, _b)

                # NO donation: the source cache must survive the read
                # (other slots keep decoding against it)
                self._handoff_fns[key] = jax.jit(
                    self._wrap_tp(run, (cs, "r"), cs))
        return self._handoff_fns[key]

    def _export_rows(self, slot, length):
        """Pull one slot's first ``length`` KV rows to host numpy
        (rounded up to the covering bucket — the decode side clips by
        position, so the pad rows are junk it never reads)."""
        bucket = self._bucket_for(length)
        tc0 = time.perf_counter()
        with tele.span("serving.handoff_export", cat="serving",
                       bucket=bucket):
            rows = self._handoff_fn(bucket)(self._caches,
                                            np.int32(slot))
            rows = jax.tree_util.tree_map(np.asarray, rows)
        self._phase_add("copy", time.perf_counter() - tc0)
        if ("handoff", bucket, "export") not in self._prog_seen:
            self._prog_seen.add(("handoff", bucket, "export"))
            profiler.register_program(
                "serving_handoff_b%d" % bucket,
                self._handoff_fns[(bucket, False)],
                (self._caches, np.int32(0)))
        return rows

    def _import_rows(self, slot, length, rows):
        """Write transferred rows into ``slot`` through the
        prefix-pool write path (dequantized to cache dtype first when
        the transfer was int8)."""
        bucket = self._bucket_for(length)
        rows = unpack_rows(rows, self._caches)
        tc0 = time.perf_counter()
        with tele.span("serving.handoff_import", cat="serving",
                       bucket=bucket):
            self._caches = self._handoff_fn(bucket, write=True)(
                self._caches, np.int32(slot), rows)
        self._phase_add("copy", time.perf_counter() - tc0)
        if ("handoff", bucket, "import") not in self._prog_seen:
            self._prog_seen.add(("handoff", bucket, "import"))
            profiler.register_program(
                "serving_handoff_wr_b%d" % bucket,
                self._handoff_fns[(bucket, True)],
                (self._caches, np.int32(0), rows))

    @property
    def compile_counts(self):
        """{'decode': n, 'verify': n, 'prefill': {bucket: n},
        'copy': {bucket: n}} — the compile-count contract: after any
        workload, decode == 1, verify <= 1 (0 with speculation off or
        never fired), each USED prefill bucket == 1 and each USED copy
        bucket == 1 (chunked prefill reuses the prefill buckets —
        chunk start is a traced operand, so chunking adds NO programs;
        one copy program covers both pool→slot and slot→pool; the ONE
        verify program serves every draft mix — drafts and their
        lengths are traced operands). Engines with ``draft="model"``
        additionally report ``'draft'`` (<= 1) and ``'draft_prefill'``
        ({bucket: 1}). Engines that ever touched the KV handoff path
        (role != "unified", or a unified engine that imported)
        additionally report ``'handoff'`` ({bucket: 1} — one row mover
        per bucket per engine; each engine only ever fires one
        DIRECTION, so export and import never share a tag).
        doc/serving.md."""
        out = {"decode": 0, "verify": 0, "prefill": {}, "copy": {}}
        if self.spec_draft == "model":
            out["draft"] = 0
            out["draft_prefill"] = {}
        if self.role != "unified" or self._handoff_fns:
            out["handoff"] = {}
        for tag in self._compile_log:
            if isinstance(tag, str):
                out[tag] += 1
            else:
                fam = out[tag[0]]
                fam[tag[1]] = fam.get(tag[1], 0) + 1
        return out

    # -- host scheduler -------------------------------------------------
    def _bucket_for(self, n):
        for b in self.prefill_buckets:
            if b >= n:
                return b
        raise MXNetError(
            "InferenceEngine: prompt length %d exceeds the largest "
            "prefill bucket %d" % (n, self.prefill_buckets[-1]))

    def _place_prompt(self, req):
        """Stager place fn: pad to the bucket and dispatch the h2d
        (async) — runs up to stage_depth requests ahead of admission.

        A prompt longer than ``prefill_chunk`` is guaranteed to admit
        as chunk pieces built at admission time (the split depends on
        the prefix match), so its full-prompt h2d would only be
        discarded — stage nothing; likewise a resumed sequence past
        the largest bucket (it admits in bucket-sized pieces). A
        prefix HIT on a short prompt also discards the staged array,
        but hits are unknowable this far ahead of admission; the waste
        there is one small int32 h2d (chunk/suffix arrays are a few KB
        — the prefill dispatch they feed dominates).

        A placement failure (a bad h2d) must poison only ITS request:
        the error rides the staged tuple to admission, where the
        request retires with reason ``"error"`` instead of unwinding
        ``step()`` from inside the stager fill."""
        th0 = time.perf_counter()
        try:
            return self._place_prompt_inner(req)
        finally:
            # the stager is inline, so fills run inside _admit and the
            # time lands on the round in flight (the _phase guard
            # drops it when no round is)
            self._phase_add("h2d", time.perf_counter() - th0)

    def _place_prompt_inner(self, req):
        try:
            p = len(req.seq)
            if (self.prefill_chunk and p > self.prefill_chunk) \
                    or p > self.prefill_buckets[-1]:
                self.flight.event(req.id, "staged", chunked=True)
                return req, None
            bucket = self._bucket_for(p)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :p] = req.seq
            # under tp the staged array must land REPLICATED on the
            # mesh (a bare device_put commits to device 0, which the
            # sharded programs would reject)
            dev = jax.device_put(padded, self._rep_shard) \
                if self._mesh is not None else jax.device_put(padded)
            self.flight.event(req.id, "staged", bucket=bucket)
            return req, dev
        except Exception as e:               # noqa: BLE001 — isolated
            self.flight.event(req.id, "staged", error=str(e))
            return req, _PlacementError(e)

    def queued(self):
        """Requests submitted but not yet admitted to a slot."""
        return len(self._pending) + self._stager.staged() \
            + (self._held is not None)

    @property
    def idle(self):
        # handoff-pinned slots count as free here: the engine has no
        # work left to STEP for them — delivery is the router's job,
        # and FleetRouter.idle separately refuses to go idle while any
        # replica still holds an unresolved package
        return not self._pending and self._stager.staged() == 0 \
            and self._held is None \
            and len(self._free) + len(self._handoff_slots) == self.slots \
            and not self._drain and not self._chunking

    def submit(self, prompt, max_tokens, eos_id=None, temperature=0.0,
               seed=None, request_id=None, deadline_ms=None,
               ttft_deadline_ms=None, _resume_tokens=(), _trace=None):
        """Queue one generation request; returns its :class:`Request`
        handle (fills in as the engine steps).

        prompt : 1-D int sequence, ``1 <= len <= max_len - 1`` (and
        within the largest bucket). ``max_tokens`` is truncated to the
        cache: at most ``max_len - len(prompt)`` tokens come back.
        ``eos_id``: generation stops after emitting it (included in
        the output). ``temperature=0``: greedy, byte-identical to
        ``Decoder.generate``; > 0 samples with ``seed`` (auto-drawn if
        omitted) — reproducible and schedule-independent.

        ``deadline_ms`` / ``ttft_deadline_ms`` (host wall clock from
        submit): past the deadline — overall, or first-token — the
        request retires at the next round boundary with
        ``retire_reason="deadline"`` and whatever tokens it generated;
        a still-QUEUED expired request is failed without ever
        occupying a slot. :meth:`cancel` retires the same way with
        ``"cancelled"``.

        A full queue follows the ``overload`` policy: ``block`` raises
        a generic ``MXNetError`` (backpressure — callers drive
        :meth:`step` to drain), ``shed`` raises a typed
        :class:`EngineOverloaded`, ``shed_oldest`` evicts the oldest
        queued request in favor of this one.
        """
        self._check_open()
        if self.draining and not _resume_tokens:
            # a draining replica takes no NEW work; resumed
            # (migrated/restored) submits still land so an operator
            # can fold work INTO an engine that is about to stop —
            # never the reverse
            raise MXNetError(
                "InferenceEngine: engine %s is draining — submit to "
                "another replica" % self.engine_id)
        if self.role == "decode":
            # decode specialists admit work through admit_handoff
            # ONLY: a fresh prompt — and equally a resumed/migrated
            # one, which re-prefills prompt+tokens on the admitting
            # engine — would trace the prefill family this role
            # exists to avoid (the FleetRouter's role-aware placement
            # never routes a submit here)
            raise MXNetError(
                "InferenceEngine: engine %s has role='decode' — "
                "prompts go to a prefill or unified replica (the "
                "FleetRouter's role-aware placement does this)"
                % self.engine_id)
        # validate shape/dtype HERE, where the caller can see the
        # problem — a bad prompt forwarded to the compiled programs
        # surfaces as an opaque shape/dtype error rounds later;
        # validation runs BEFORE the overload branch so an
        # inadmissible submit can never shed valid queued work
        try:
            prompt = np.asarray(prompt)
        except Exception as e:
            raise MXNetError(
                "InferenceEngine: prompt is not array-like (%s)" % e)
        if prompt.ndim != 1:
            raise MXNetError(
                "InferenceEngine: prompt must be a 1-D token sequence "
                "(one request per submit), got shape %r"
                % (prompt.shape,))
        if prompt.size < 1:
            raise MXNetError("InferenceEngine: empty prompt")
        if not np.issubdtype(prompt.dtype, np.integer):
            raise MXNetError(
                "InferenceEngine: prompt token ids must be integers, "
                "got dtype %s (floats would be silently truncated)"
                % prompt.dtype)
        prompt = prompt.astype(np.int32)
        if prompt.size + len(_resume_tokens) > self.max_len - 1:
            raise MXNetError(
                "InferenceEngine: prompt length %d leaves no room to "
                "generate (max_len=%d)" % (prompt.size, self.max_len))
        if not self.prefill_chunk and not _resume_tokens:
            # monolithic prefill must fit one bucket program; chunked
            # engines serve ANY prompt <= max_len - 1 in pieces (each
            # piece <= prefill_chunk <= the largest bucket), and a
            # RESUMED sequence admits in bucket-sized pieces even with
            # chunking off (restore() must never reject what the
            # crashed engine had accepted)
            self._bucket_for(prompt.size)
        max_tokens = int(max_tokens)
        if max_tokens < 1:
            raise MXNetError("InferenceEngine: max_tokens must be >= 1")
        # eos/temperature validation HERE too (same reasoning as the
        # prompt checks): a vector eos or NaN temperature forwarded as
        # a traced operand misbehaves downstream — a NaN softmax draw,
        # a shape error rounds later — with no pointer back to the
        # offending submit
        if eos_id is not None:
            try:
                e = np.asarray(eos_id)
            except Exception:
                e = None
            if e is None or e.ndim != 0 \
                    or not np.issubdtype(e.dtype, np.integer):
                raise MXNetError(
                    "InferenceEngine: eos_id must be a scalar integer "
                    "token id, got %r" % (eos_id,))
            eos_id = int(e)
            if eos_id < 0:
                raise MXNetError(
                    "InferenceEngine: eos_id must be >= 0, got %d "
                    "(negative ids collide with the engine's 'no eos' "
                    "sentinel)" % eos_id)
        try:
            temp = float(temperature)
        except (TypeError, ValueError):
            temp = float("nan")          # rejected just below
        if math.isnan(temp) or math.isinf(temp) or temp < 0:
            raise MXNetError(
                "InferenceEngine: temperature must be a finite float "
                ">= 0, got %r (0 = greedy)" % (temperature,))
        temperature = temp
        if self.queued() >= self.max_queue:
            if self.overload == "shed_oldest" and self._shed_oldest():
                pass                     # room made; admit the new one
            elif self.overload in ("shed", "shed_oldest"):
                _TM_SHED.inc()
                self.stats["shed"] += 1
                raise EngineOverloaded(
                    "InferenceEngine: overloaded — %d requests waiting "
                    "(max_queue=%d, overload=%r); retry against "
                    "another replica or back off"
                    % (self.queued(), self.max_queue, self.overload))
            else:
                raise MXNetError(
                    "InferenceEngine: request queue is full (%d "
                    "waiting; max_queue=%d) — step() the engine to "
                    "drain it" % (self.queued(), self.max_queue))
        if seed is None:
            seed = self._auto_seed
            self._auto_seed += 1
        rid = request_id
        if rid is None:
            rid = self._next_id
            self._next_id += 1
        limit = min(max_tokens, self.max_len - prompt.size)
        req = Request(rid, prompt, max_tokens, eos_id,
                      temperature, seed, limit,
                      deadline_ms=deadline_ms,
                      ttft_deadline_ms=ttft_deadline_ms,
                      resume_tokens=_resume_tokens)
        if _trace is not None:
            req.trace = (str(_trace[0]), int(_trace[1]))
        self._pending.append(req)
        self._active[rid] = req
        if req._deadline is not None or req._ttft_deadline is not None:
            self._watched.add(rid)
        self.stats["submitted"] += 1
        self.capture.submit(req)
        if self.flight.enabled:
            meta = {"prompt_len": int(prompt.size),
                    "max_tokens": max_tokens}
            if temperature:
                meta["temperature"] = temperature
            if req.resumed:
                meta["resumed"] = req.resumed
            if deadline_ms is not None:
                meta["deadline_ms"] = deadline_ms
            if ttft_deadline_ms is not None:
                meta["ttft_deadline_ms"] = ttft_deadline_ms
            if req.trace is not None:
                meta["trace"], meta["hop"] = req.trace
            self.flight.start(rid, **meta)
        return req

    def cancel(self, request_id):
        """Cancel a queued or in-flight request: it retires at the
        next round boundary with ``retire_reason="cancelled"`` and
        whatever tokens already drained (``result()`` returns them); a
        still-queued request never occupies a slot. Returns True if
        the request was live, False if unknown or already done."""
        req = self._active.get(request_id)
        if req is None or req.done:
            return False
        req._cancelled = True
        self._watched.add(request_id)
        return True

    # -- KV handoff scheduler seams -------------------------------------
    def _handoff_prefill(self, req, slot, t0, now):
        """Prefill-role drain tail: the first token lands on the
        request exactly as unified serving would land it (TTFT is
        SERVED here — the decode side inherits it), then the finished
        prefill is packaged for the router. The slot leaves the free
        list into ``_handoff_slots`` — its KV rows must survive until
        the package resolves — and the request retires locally with
        ``retire_reason="handoff"`` (the FleetRequest facade treats
        that as still-running)."""
        self._push_token(req, slot, t0, now)
        if req.done:
            return          # eos / one-token limit on t0: completed
                            # here, nothing left to hand off (the slot
                            # was released by _push_token)
        pkg = KVHandoff(self, req, slot)
        self._handoff_slots.add(slot)
        self._handoff_out.append(pkg)
        self.stats["handoffs_out"] += 1
        self.flight.event(req.id, "handoff_export", slot=slot,
                          prefill_len=pkg.prefill_len)
        self._finish(req, "handoff")

    def take_handoffs(self):
        """Drain the packaged finished prefills (router-facing). The
        caller OWNS delivery: every returned package must eventually
        be ``resolve()``d — delivered, deduped, or abandoned — or its
        slot stays pinned forever."""
        out = []
        while self._handoff_out:
            out.append(self._handoff_out.popleft())
        return out

    def _resolve_handoff(self, pkg):
        """Release a package's slot, exactly once (KVHandoff.resolve
        target). Double resolution is a transport-discipline bug —
        refuse loudly rather than corrupt the free list."""
        if pkg.resolved:
            raise MXNetError(
                "InferenceEngine: handoff package %r resolved twice — "
                "each package has exactly one terminal path" % (pkg,))
        pkg.resolved = True
        if pkg.slot in self._handoff_slots:
            self._handoff_slots.discard(pkg.slot)
            self._release_slot(pkg.slot)

    def set_role(self, role):
        """Widen a specialist to ``"unified"`` (failover promotion:
        the survivor of a dead prefill/decode pair serves both phases;
        any program family it is missing compiles lazily on first
        use). Narrowing a live engine is refused — slots may hold
        state the narrower role could never have produced."""
        if role == self.role:
            return
        if role != "unified":
            raise MXNetError(
                "InferenceEngine: role can only widen to 'unified' "
                "(engine %s is %r, asked for %r) — build a new engine "
                "to specialize" % (self.engine_id, self.role, role))
        self.role = "unified"
        _TM_ROLE.set(0)

    def admit_handoff(self, payload, deadline_ms=None,
                      ttft_deadline_ms=None):
        """Admit a handed-off finished prefill (router-facing): write
        the transferred KV rows into a free slot through the
        prefix-pool write path — or skip the write entirely when
        ``payload["rows"]`` is None because this engine's prefix pool
        already retains the full prefill — poke the slot's scheduler
        state to resume AFTER the prefill's first token, and continue
        decoding byte-identically to a unified engine.

        Exactly-once under retries: a package id already active or
        already imported returns the existing request without touching
        the cache (the router's retry ambiguity resolves here, the
        ``_channel_submit`` adoption discipline). Raises
        :class:`EngineOverloaded` when no slot is free — the router
        tries the next decode replica or waits."""
        self._check_open()
        if self.role == "prefill":
            raise MXNetError(
                "InferenceEngine: engine %s has role='prefill' — it "
                "exports handoffs, it cannot admit one"
                % self.engine_id)
        rid = payload["id"]
        existing = self._active.get(rid)
        if existing is not None:
            return existing
        existing = self._imported.get(rid)
        if existing is not None:
            return existing
        # Flush every dispatched-but-undrained round BEFORE touching a
        # slot: those rounds saw the slot device-dead (-1 sentinel) and
        # must not drain after the mirror names the imported request —
        # the same hazard the submit path avoids by deferring its
        # mirror write to prefill-drain time. Draining may also retire
        # finished requests and free slots, so it runs before the
        # overload check.
        while self._drain:
            self._drain_one()
        if not self._free:
            raise EngineOverloaded(
                "InferenceEngine: engine %s has no free slot for a "
                "handoff (slots=%d busy)" % (self.engine_id,
                                             self.slots))
        prompt = np.asarray(payload["prompt"], np.int32)
        tokens = [int(t) for t in payload["tokens"]]
        if not tokens:
            raise MXNetError(
                "InferenceEngine: handoff payload %r carries no first "
                "token — the prefill side emits it" % (rid,))
        req = Request(rid, prompt, int(payload["max_tokens"]),
                      payload["eos_id"], float(payload["temperature"]),
                      int(payload["seed"]),
                      min(int(payload["max_tokens"]),
                          self.max_len - prompt.size),
                      deadline_ms=deadline_ms,
                      ttft_deadline_ms=ttft_deadline_ms,
                      resume_tokens=tokens)
        # TTFT was served on the prefill engine; mark it attained so
        # cadence math never divides by a first-token gap this engine
        # did not serve
        req.t_first = req.t_submit
        trace = payload.get("trace")
        if trace is not None:
            # the wire crossing is one hop: the decode-side record
            # carries hop+1 relative to the exporting prefill engine
            req.trace = (str(trace[0]), int(trace[1]) + 1)
        P = int(payload["prefill_len"])
        if P != len(req.seq) - 1:
            raise MXNetError(
                "InferenceEngine: handoff payload %r is inconsistent — "
                "prefill_len=%d but prompt+tokens cover %d positions "
                "(+1 for the first emitted token)"
                % (rid, P, len(req.seq)))
        if P > self.prefill_buckets[-1] or payload["last"] >= self.max_len:
            raise MXNetError(
                "InferenceEngine: handoff %r does not fit this "
                "engine's geometry (prefill_len=%d, last=%d vs "
                "buckets %r, max_len=%d) — replicas in one fleet share "
                "geometry" % (rid, P, payload["last"],
                              self.prefill_buckets, self.max_len))
        slot = self._free.popleft()
        req.t_admit = time.perf_counter()
        rows = payload.get("rows")
        entry = None
        try:
            if rows is None:
                # transfer skipped on prefix affinity: the router saw
                # this engine's pool retaining the full prefill. The
                # pin brackets the copy dispatch (PR 7 discipline).
                if self._prefix is None:
                    raise MXNetError(
                        "InferenceEngine: rows-less handoff %r but "
                        "engine %s has no prefix pool"
                        % (rid, self.engine_id))
                depth, entry = self._prefix.lookup(req.seq[:P])
                if depth < P or entry is None:
                    raise MXNetError(
                        "InferenceEngine: rows-less handoff %r but "
                        "the pool covers only %d of %d prefill "
                        "positions — the router's affinity probe was "
                        "stale; retry with rows" % (rid, depth, P))
                self._prefix.acquire(entry)
                self._dispatch_copy(P, src=entry.slot, dst=slot,
                                    src_pool=True, dst_pool=False)
                self._prefix.release(entry)
                entry = None
                self.stats["prefix_hits"] += 1
                self.stats["prefix_hit_tokens"] += P
                _TM_PREFIX_HITS.inc()
                _TM_PREFIX_HIT_TOKENS.inc(P)
            else:
                self._import_rows(slot, P, rows)
            # scheduler-state poke: resume exactly where the unified
            # engine's prefill program would have left this slot
            # (pos=P, tok=t_last, live, the sampling identity, and the
            # same lastp clamp _prefill_fn computes)
            vals = (np.int32(P), np.int32(tokens[-1]), True,
                    np.float32(req.temperature), _raw_key(req.seed),
                    np.int32(-1 if req.eos_id is None else req.eos_id),
                    np.int32(payload["last"]))
            new_state = Decoder.slot_set_state(self._state, slot, vals)
            if self._mesh is not None:
                new_state = tuple(
                    jax.device_put(a, self._rep_shard)
                    for a in new_state)
            self._state = new_state
        except Exception:
            if entry is not None:
                self._prefix.release(entry)
            self._release_slot(slot)
            self._free.remove(slot)      # popleft put-back, FIFO head
            self._free.appendleft(slot)
            raise
        self._mirror[slot] = req
        self._active[rid] = req
        if req._deadline is not None or req._ttft_deadline is not None:
            self._watched.add(rid)
        if self.spec_draft == "ngram":
            self._drafters[rid] = NgramDrafter(req.seq)
        elif self.spec_draft == "model":
            self._draft_prefill_all(req, slot)
        # decode-side retention: park the prefill in THIS engine's
        # pool so the next same-prefix handoff ships no rows at all
        # (the router's affinity probe finds it via peek)
        if rows is not None and self._prefix is not None \
                and not self._pressure \
                and P <= self.prefill_buckets[-1] \
                and self._prefix.get(req.seq[:P]) is None:
            try:
                new = self._prefix.insert(req.seq[:P])
                if new is not None:
                    try:
                        self._dispatch_copy(P, src=slot, dst=new.slot,
                                            src_pool=False,
                                            dst_pool=True)
                    except Exception:
                        self._prefix.discard(new)
                        raise
                _TM_PREFIX_BYTES.set(self._prefix.bytes_used)
            except Exception:            # noqa: BLE001 — isolated
                _TM_PREFIX_INSERT_SKIPPED.inc()
        self.stats["handoffs_in"] += 1
        self.stats["submitted"] += 1
        self.capture.submit(req)
        if self.flight.enabled:
            meta = {"prompt_len": int(prompt.size),
                    "max_tokens": int(payload["max_tokens"]),
                    "handoff": True, "resumed": req.resumed}
            if req.trace is not None:
                meta["trace"], meta["hop"] = req.trace
            self.flight.start(rid, **meta)
            self.flight.event(rid, "handoff_import", slot=slot,
                              prefill_len=P,
                              rows=rows is not None)
        self._imported[rid] = req
        while len(self._imported) > 256:
            self._imported.popitem(last=False)
        return req

    # -- lifecycle: retirement, shedding, shutdown ----------------------
    def _check_open(self):
        if self._closed:
            raise EngineClosed(
                "InferenceEngine is closed — build a new engine (or "
                "restore() a snapshot)")

    def _release_slot(self, slot):
        """Host-side slot release — the same freeze contract device
        retirement uses: the device copy may still be live (it keeps
        decoding its dead request harmlessly until its own budget, or
        until the next occupant's prefill scatter overwrites its state
        and rows), and pending drain entries for it drop their tokens
        through the cleared mirror. Purely host bookkeeping: no device
        op, no new program."""
        self._mirror[slot] = None
        self._free.append(slot)

    def _finish(self, req, reason, error=None):
        """Common retirement tail for every host-side path; the
        request is handed back by the next ``step()`` return."""
        req.done = True
        req.t_done = time.perf_counter()
        req.retire_reason = reason
        req.error = error
        self._active.pop(req.id, None)
        self._watched.discard(req.id)
        self._drafters.pop(req.id, None)
        if self.flight.enabled:
            extra = {"tokens": len(req.tokens)}
            if error is not None:
                extra["error"] = str(error)
            self.flight.retire(req.id, reason, **extra)
        # a TTFT SLO cannot be attained by a request that died without
        # a first token: count the deadline retirement as a miss (the
        # burn gauges, derived from the TTFT histogram, only see
        # SERVED requests — doc/observability.md notes the split)
        if self.slo_ttft_ms is not None and req.t_first is None \
                and reason == "deadline":
            _TM_SLO_TTFT_MISS.inc()
        self.capture.retire(req)
        if reason == "deadline":
            _TM_DEADLINE.inc()
            self.stats["deadline_missed"] += 1
        elif reason == "cancelled":
            _TM_CANCELLED.inc()
            self.stats["cancelled"] += 1
        elif reason == "shed":
            _TM_SHED.inc()
            self.stats["shed"] += 1
        elif reason == "error":
            _TM_ERRORS.inc()
            self.stats["errors"] += 1
        self._done_buf.append(req)

    def _retire_active(self, req, reason, error=None):
        """Detach ``req`` from whichever scheduler structure holds it
        (queue, stager, held buffer, chunking queue, drain queue, or a
        decoding slot), releasing its slot and prefix-cache pin. The
        slot-recycle argument is `_release_slot`'s; prefix pins are
        released on EVERY path (a leaked pin would starve the pool)."""
        try:
            self._pending.remove(req)
        except ValueError:
            pass
        self._stager.prune(lambda item: item[0] is req)
        if self._held is not None and self._held[0] is req:
            self._held = None
        for st in list(self._chunking):
            if st["req"] is req:
                self._chunking.remove(st)
                if st["entry"] is not None:
                    self._prefix.release(st["entry"])
                    st["entry"] = None
                self._release_slot(st["slot"])
        for entry in self._drain:
            if entry[0] == "prefill" and entry[1] is req:
                # the staged first token is dropped at drain time (the
                # req is done); the slot frees NOW — FIFO draining
                # keeps any reuse ordered behind this entry
                self._release_slot(entry[2])
        for s in range(self.slots):
            if self._mirror[s] is req:
                self._release_slot(s)
        self._finish(req, reason, error)

    def _shed_oldest(self, why="under overload='shed_oldest' (newer "
                                "work displaced it)"):
        """Evict the oldest QUEUED (never admitted) request to make
        room (overload="shed_oldest") or to drop an unadmitted backlog
        (``why`` names the cause on the victim's error). Admitted work
        is never shed — its prefill is sunk cost. Age order: the held
        admission candidate (popped from the stager earliest), then
        staged items, then the pending deque. Returns True if one was
        shed."""
        victim = None
        if self._held is not None:
            victim = self._held[0]
        elif self._stager.staged():
            first = []

            def oldest(item):       # one-shot: prune is single-pass
                if first:
                    return False
                first.append(item)
                return True

            dropped = self._stager.prune(oldest)
            if dropped:
                victim = dropped[0][0]
        if victim is None and self._pending:
            victim = self._pending[0]
        if victim is None:
            return False
        self._retire_active(victim, "shed", EngineOverloaded(
            "InferenceEngine: request %r shed %s" % (victim.id, why)))
        return True

    def _sweep(self):
        """Round-boundary lifecycle sweep: retire cancelled and
        deadline-expired requests. Only ``_watched`` ids are visited,
        so deadline-less traffic pays nothing."""
        if not self._watched:
            return
        now = time.perf_counter()
        for rid in list(self._watched):
            req = self._active.get(rid)
            if req is None or req.done:
                self._watched.discard(rid)
                continue
            if req._cancelled:
                self._retire_active(req, "cancelled")
            elif req._expired(now):
                self._retire_active(req, "deadline")

    @property
    def _pressure(self):
        """Overloaded right now? Under a shedding policy this pauses
        prefix-cache retention (the slot→pool copy dispatch competes
        with serving work exactly when there is least room for it)."""
        return self.overload != "block" \
            and self.queued() >= self.max_queue

    def _admit(self):
        """Fill freed slots from the staged queue, between device
        steps (iteration-level scheduling). Admission = prefix-cache
        lookup (longest retained prefix → one compiled row copy into
        the slot) + the FIRST prefill piece of the uncovered suffix;
        further pieces run one budget's worth per round via the
        chunking queue. Under chunking, each admission's first piece
        draws from the round's prefill-token budget — a burst of
        arrivals admits only as much prefill work per round as the
        budget allows (the held request resumes first next round, so
        FIFO order is preserved). Returns how many requests were
        admitted."""
        admitted = 0
        now = time.perf_counter()
        while self._free:
            if self._held is not None:
                req, dev, self._held = \
                    self._held[0], self._held[1], None
            else:
                try:
                    req, dev = self._stager.next()
                except StopIteration:
                    break
            if req.done:
                continue            # retired while staged (shed/close)
            if req._cancelled or req._expired(now):
                # queue-waiting expiry: failed WITHOUT occupying a slot
                self._finish(req, "cancelled" if req._cancelled
                             else "deadline")
                continue
            if isinstance(dev, _PlacementError):
                self._finish(req, "error", MXNetError(
                    "InferenceEngine: request %r failed h2d staging "
                    "(%s)" % (req.id, dev.error)))
                continue
            p = len(req.seq)
            try:
                hit, entry, depth = 0, None, 0
                if self._prefix is not None:
                    tl0 = time.perf_counter()
                    with tele.span("serving.prefix_lookup",
                                   cat="serving",
                                   hist=_TM_PREFIX_LOOKUP_MS):
                        depth, entry = self._prefix.lookup(req.seq)
                    self._phase_add("prefix_lookup",
                                    time.perf_counter() - tl0)
                    # a FULL hit still re-prefills the last prompt
                    # token: the cache retains K/V only, and the first
                    # generated token needs the last position's logits
                    hit = min(depth, p - 1)
                    # a hit only pays when it REDUCES prefill work
                    # (fewer padded tokens across the piece split);
                    # otherwise the copy dispatch is pure overhead on
                    # top of the same bucket-quantized prefill — treat
                    # as miss
                    if hit > 0 and self._suffix_cost(p - hit) \
                            >= self._suffix_cost(p):
                        hit, entry = 0, None
            except Exception as e:       # noqa: BLE001 — trie fault
                # a corrupt trie poisons THIS request, not the engine:
                # no slot was taken, nothing was pinned
                self._finish(req, "error", MXNetError(
                    "InferenceEngine: prefix-cache lookup failed for "
                    "request %r (%s)" % (req.id, e)))
                continue
            first_piece = min(p - hit, self.prefill_chunk or p - hit)
            if first_piece > self._round_budget:
                # this round's prefill budget is spent: hold the
                # request (admitted next round, before newer arrivals)
                self._held = (req, dev)
                break
            slot = self._free.popleft()
            req.t_admit = time.perf_counter()
            _TM_QUEUE_WAIT_MS.observe(
                (req.t_admit - req.t_submit) * 1e3)
            self.flight.event(
                req.id, "admitted", slot=slot,
                queue_wait_ms=round(
                    (req.t_admit - req.t_submit) * 1e3, 3))
            st = {"req": req, "slot": slot, "dev": dev, "next": hit,
                  "entry": None,
                  # retain only prompts no entry already covers whole
                  # (a second copy buys nothing) that fit the copy
                  # bucket family (longer chunked prompts stay
                  # unretained — their prefixes can still hit via
                  # shorter entries); the overload-pressure pause is
                  # checked at the retention DISPATCH instead (the
                  # final chunk may land rounds after admission)
                  "insert": self._prefix is not None and depth < p
                  and p <= self.prefill_buckets[-1]}
            try:
                if self.spec_draft == "ngram":
                    # drafter context = prompt + emitted so far (the
                    # resumed suffix rides in req.seq); drained tokens
                    # append in _push_token
                    self._drafters[req.id] = NgramDrafter(req.seq)
                elif self.spec_draft == "model":
                    self._draft_prefill_all(req, slot)
                if self._prefix is not None:
                    if hit > 0:
                        self._prefix.acquire(entry)
                        st["entry"] = entry
                        req.prefix_hit_tokens = hit
                        self.stats["prefix_hits"] += 1
                        self.stats["prefix_hit_tokens"] += hit
                        _TM_PREFIX_HITS.inc()
                        _TM_PREFIX_HIT_TOKENS.inc(hit)
                        self.flight.event(req.id, "prefix_hit",
                                          tokens=hit)
                        self._dispatch_copy(hit, src=entry.slot,
                                            dst=slot, src_pool=True,
                                            dst_pool=False)
                    else:
                        _TM_PREFIX_MISSES.inc()
                        self.flight.event(req.id, "prefix_miss")
                if not self._advance_chunk(st):
                    self._chunking.append(st)
            except Exception as e:       # noqa: BLE001 — poisoned
                self._poison(st, e)
            admitted += 1
        return admitted

    def _poison(self, st, exc):
        """A per-request host-side failure (bad h2d, chunk math, copy
        dispatch) retires ONLY that request: its slot is released, its
        prefix pin dropped, the error carried on the request — the
        co-resident slots' requests never notice (acceptance-pinned in
        tests/test_serving_faults.py)."""
        if st["entry"] is not None:
            self._prefix.release(st["entry"])
            st["entry"] = None
        self._release_slot(st["slot"])
        req = st["req"]
        self._finish(req, "error", MXNetError(
            "InferenceEngine: request %r poisoned during admission/"
            "prefill (%s: %s) — retired alone, engine keeps serving"
            % (req.id, type(exc).__name__, exc)))

    def _draft_prefill_all(self, req, slot):
        """Build the DRAFT model's cache for a freshly admitted slot:
        the whole ``req.seq`` in bucket-capped pieces, dispatched at
        admission (the draft model is a fraction of the target's
        FLOPs, so it is not chunk-budgeted like target prefill; it
        also ignores prefix hits — the pool holds target K/V only).
        Resets the slot's draft clock and pending-token queue."""
        p = len(req.seq)
        start = 0
        top = self.prefill_buckets[-1]
        td0 = time.perf_counter()
        while start < p:
            piece = min(p - start, top)
            bucket = self._bucket_for(piece)
            chunk = np.zeros((1, bucket), np.int32)
            chunk[0, :piece] = req.seq[start:start + piece]
            self._draft_caches = self._draft_prefill_fn(bucket)(
                self._draft_params, self._draft_aux,
                self._draft_caches, np.int32(slot), chunk,
                np.int32(start), np.int32(piece))
            start += piece
        self._phase_add("prefill", time.perf_counter() - td0)
        self._draft_pos[slot] = p
        self._draft_pending[slot] = []

    def _suffix_cost(self, n):
        """Prefill-work proxy for an ``n``-token suffix: total PADDED
        tokens across its piece split — what bucket quantization
        actually charges for (piece count alone would demote every hit
        whose suffix and full prompt both fit one chunk). Splits
        exactly like :meth:`_advance_chunk`: chunking off still caps
        pieces at the largest bucket (resumed sequences can exceed
        it)."""
        chunk = self.prefill_chunk or self.prefill_buckets[-1]
        total = 0
        while n > 0:
            piece = min(n, chunk)
            total += self._bucket_for(piece)
            n -= piece
        return total

    def _advance_chunk(self, st):
        """Dispatch the next prefill piece for an admitted request:
        the whole remaining suffix when chunking is off (or it fits),
        else one ``prefill_chunk``-sized piece (a RESUMED sequence
        longer than the largest bucket splits into bucket-sized pieces
        even with chunking off — same programs, same park-dead
        contract between pieces). The FINAL piece samples the first
        token in-program and (prefix cache on) retains the freshly
        built prompt K/V in the pool. Returns True once the final
        piece is dispatched. Exceptions poison only this request — the
        caller routes them to :meth:`_poison`."""
        req, slot = st["req"], st["slot"]
        flt = _SERVING_FAULTS
        if flt is not None:
            flt.serving_h2d(req)         # injected per-request fault
        params, aux = self._params, self._aux
        start = st["next"]
        p = len(req.seq)
        remaining = p - start
        piece = min(remaining,
                    self.prefill_chunk or self.prefill_buckets[-1])
        final = start + piece == p
        if start == 0 and piece == p and st["dev"] is not None:
            dev = st["dev"]            # staged whole-prompt h2d
            bucket = int(dev.shape[1])
        else:
            bucket = self._bucket_for(piece)
            chunk = np.zeros((1, bucket), np.int32)
            chunk[0, :piece] = req.seq[start:start + piece]
            dev = chunk
        fn = self._prefill_fn(bucket)
        tp0 = time.perf_counter()
        with tele.span("serving.prefill", cat="serving", bucket=bucket,
                       slot=slot, start=start):
            self._caches, self._state, t0 = fn(
                params, aux, self._caches, self._state,
                np.int32(slot), dev, np.int32(start), np.int32(piece),
                np.bool_(final), np.float32(req.temperature),
                _raw_key(req.seed),
                np.int32(-1 if req.eos_id is None else req.eos_id),
                np.int32(req.limit - req.resumed))
        self._phase_add("prefill", time.perf_counter() - tp0)
        if ("prefill", bucket) not in self._prog_seen:
            self._prog_seen.add(("prefill", bucket))
            # post-dispatch arrays carry the same avals the dispatch
            # traced with (the pre-call ones may be donated) — the
            # registry converts to ShapeDtypeStructs immediately
            profiler.register_program(
                "serving_prefill_b%d" % bucket, fn,
                (params, aux, self._caches, self._state, np.int32(0),
                 np.zeros((1, bucket), np.int32), np.int32(0),
                 np.int32(1), np.bool_(True), np.float32(0),
                 _raw_key(0), np.int32(-1), np.int32(1)))
        self.flight.event(req.id, "prefill_chunk", start=start,
                          tokens=piece, bucket=bucket,
                          final=bool(final))
        req.prefill_chunks += 1
        st["next"] = start + piece
        self.stats["prefill_chunks"] += 1
        self._round_budget -= piece
        if not final:
            return False
        self._drain.append(("prefill", req, slot, t0))
        self.stats["prefills"] += 1
        _TM_PREFILLS.inc()
        _TM_CHUNKS.observe(req.prefill_chunks)
        if st["entry"] is not None:
            self._prefix.release(st["entry"])
            st["entry"] = None
        # a duplicate prompt admitted while this one was mid-chunk may
        # have finished first and retained the same tokens — its rows
        # are already byte-identical, so re-copying is a wasted
        # dispatch. Retention failures are NON-fatal: the request has
        # its token coming — drop the half-made entry (its rows never
        # materialized) and skip.
        try:
            # pressure is re-checked NOW, not at admission: the slot→
            # pool copy competes with serving exactly when the queue
            # is full at dispatch time (and transient pressure back at
            # admission shouldn't suppress a retention the engine has
            # room for by the final chunk)
            if st["insert"] and not self._pressure \
                    and self._prefix.get(req.seq) is None:
                ev0 = self._prefix.evictions
                new = self._prefix.insert(req.seq)
                _TM_PREFIX_EVICTIONS.inc(self._prefix.evictions - ev0)
                if new is None:
                    _TM_PREFIX_INSERT_SKIPPED.inc()
                else:
                    self.flight.event(req.id, "retained", tokens=p)
                    try:
                        # the slot's rows [0, P) ARE the prompt K/V
                        # right now — the retention copy is ordered
                        # before the slot's decode writes by the
                        # cache-tree data dependency
                        self._dispatch_copy(p, src=slot, dst=new.slot,
                                            src_pool=False,
                                            dst_pool=True)
                    except Exception:
                        self._prefix.discard(new)
                        raise
                _TM_PREFIX_BYTES.set(self._prefix.bytes_used)
        except Exception:                # noqa: BLE001 — isolated
            _TM_PREFIX_INSERT_SKIPPED.inc()
        return True

    def _busy(self):
        return (self.slots - len(self._free)
                - len(self._handoff_slots)) > 0 \
            or bool(self._pending) \
            or self._stager.staged() > 0 or self._held is not None

    def _push_token(self, req, slot, t, now):
        assert t >= 0, "drained a token from a device-dead slot"
        req.tokens.append(int(t))
        if self._spec:
            dr = self._drafters.get(req.id)
            if dr is not None:
                dr.append(t)        # n-gram context stays current
            if self._draft_dec is not None:
                # the draft cache catches up on this token before the
                # next proposal (_model_drafts)
                self._draft_pending[slot].append(int(t))
        if req.t_first is None:
            req.t_first = now
            ttft_ms = (now - req.t_submit) * 1e3
            _TM_TTFT_MS.observe(ttft_ms)
            if self.slo_ttft_ms is not None:
                (_TM_SLO_TTFT_OK if ttft_ms <= self.slo_ttft_ms
                 else _TM_SLO_TTFT_MISS).inc()
            self.flight.event(req.id, "first_token",
                              ttft_ms=round(ttft_ms, 3))
        else:
            self.flight.token(req.id, len(req.tokens))
        self.stats["tokens"] += 1
        _TM_TOKENS.inc()
        hit_eos = req.eos_id is not None and t == req.eos_id
        if hit_eos or len(req.tokens) >= req.limit:
            req.done = True
            req.t_done = now
            req.retire_reason = "eos" if hit_eos else "length"
            (_TM_RETIRED_EOS if hit_eos else _TM_RETIRED_LENGTH).inc()
            _TM_COMPLETED.inc()
            self._drafters.pop(req.id, None)
            # cadence = wall time per decode interval THIS engine ran:
            # a resumed request's pre-crash tokens arrived before
            # t_first and must not inflate the denominator
            if len(req.tokens) - req.resumed > 1:
                cadence_ms = ((req.t_done - req.t_first)
                              / (len(req.tokens) - req.resumed - 1)
                              * 1e3)
                _TM_CADENCE_MS.observe(cadence_ms)
                if self.slo_cadence_ms is not None:
                    (_TM_SLO_CAD_OK
                     if cadence_ms <= self.slo_cadence_ms
                     else _TM_SLO_CAD_MISS).inc()
            self._active.pop(req.id, None)
            self._watched.discard(req.id)
            self._release_slot(slot)
            self.stats["completed"] += 1
            self.capture.retire(req)
            self.flight.retire(req.id, req.retire_reason,
                               tokens=len(req.tokens))
            self._done_buf.append(req)

    def _guard_ready(self, arrays):
        """Round watchdog: with ``round_timeout_ms`` set, poll the
        drain head's device buffers host-side and raise a typed
        :class:`EngineStuck` instead of letting the d2h conversion
        block forever on a wedged dispatch. The undrained entry stays
        queued — a recovered device drains it on the next step."""
        if self.round_timeout_ms <= 0:
            return
        flt = _SERVING_FAULTS
        deadline = time.perf_counter() + self.round_timeout_ms / 1e3
        while True:
            stuck = flt is not None and flt.serving_round_stuck()
            if not stuck and Decoder.buffers_ready(arrays):
                return
            if time.perf_counter() >= deadline:
                _TM_WATCHDOG.inc()
                self.stats["watchdog_trips"] += 1
                self._watchdog_stuck_t = time.perf_counter()
                raise EngineStuck(
                    "InferenceEngine: dispatched round not ready after "
                    "round_timeout_ms=%g — device stuck or overloaded. "
                    "step() again to retry the drain, or snapshot()/"
                    "restore() onto a fresh engine"
                    % self.round_timeout_ms)
            time.sleep(0.001)

    def _phase_add(self, key, dt):
        """Attribute ``dt`` seconds of the in-flight round to a phase
        (no-op outside step() — e.g. a submit-path capture write)."""
        acc = self._phase
        if acc is not None:
            acc[key] = acc.get(key, 0.0) + dt

    def _drain_one(self):
        t0 = time.perf_counter()
        try:
            self._drain_one_inner()
        finally:
            self._phase_add("drain", time.perf_counter() - t0)

    def _drain_one_inner(self):
        entry = self._drain[0]       # peek: a watchdog trip must not
        self._guard_ready(entry[3] if entry[0] == "prefill"
                          else entry[1])  # lose the undrained round
        self._watchdog_stuck_t = None    # drained: device recovered
        self._drain.popleft()
        now = time.perf_counter()
        if entry[0] == "prefill":
            _, req, slot, t0 = entry
            if req.done:
                return               # host-retired while staged: the
                                     # slot was already released
            if self.role == "prefill":
                self._handoff_prefill(req, slot, int(np.asarray(t0)),
                                      now)
                return
            self._mirror[slot] = req
            self._push_token(req, slot, int(np.asarray(t0)), now)
        elif entry[0] == "verify":
            # [<=K+1, S] variable-width drain: row i is the i-th token
            # a slot emitted this verify round, -1 where its accepted
            # prefix ended (a slot that had no draft emits exactly
            # row 0 — its plain-decode token). Accepted drafts =
            # emitted - 1, observed per drafted slot.
            rows, dlen = np.asarray(entry[1]), entry[2]
            emitted = np.zeros((self.slots,), np.int64)
            for row in rows:
                for s in range(self.slots):
                    req = self._mirror[s]
                    t = int(row[s])
                    if req is None or t < 0:
                        continue
                    emitted[s] += 1
                    self._push_token(req, s, t, now)
            acc = 0
            for s in range(self.slots):
                if dlen[s] > 0 and emitted[s] > 0:
                    a = int(emitted[s]) - 1
                    acc += a
                    _TM_SPEC_ACCEPT_LEN.observe(a)
            if acc:
                self.stats["spec_accepted"] += acc
                _TM_SPEC_ACCEPTED.inc(acc)
        else:
            rounds = np.asarray(entry[1])        # [steps_per_round, S]
            for row in rounds:
                for s in range(self.slots):
                    req = self._mirror[s]
                    if req is not None:
                        self._push_token(req, s, int(row[s]), now)

    def _spec_round(self, busy):
        """Try to dispatch ONE verify round (doc/serving.md
        "Speculative decoding"): collect up to ``spec_k`` draft tokens
        per decodable slot from the configured drafter, and if at
        least one slot has a draft, run the verify program — one
        chunked target dispatch emitting each slot's accepted prefix
        plus one corrected token (``[<=K+1, S]`` drain). Returns False
        (→ the caller dispatches the plain decode round, counted as a
        fallback) when no slot drafted, or when ANY occupied slot sits
        too near the cache end for the fixed-width chunk write
        (``dynamic_update_slice`` clamps an out-of-range start, which
        would shift the write onto live rows — the last few tokens of
        a near-``max_len`` sequence always decode plainly)."""
        K = self.spec_k
        S = self.slots
        parts = []
        for s in range(S):
            req = self._mirror[s]
            if req is None:
                continue
            # the slot's device position (exact: spec drains eagerly)
            pos = len(req.seq) + len(req.tokens) - req.resumed - 1
            if pos + K + 2 > self.max_len:
                return False
            k_s = min(K, req.limit - len(req.tokens) - 1)
            if k_s > 0:
                parts.append((s, req, k_s))
        for st in self._chunking:
            # parked mid-prefill slots ride the chunk write too
            if st["next"] + K + 2 > self.max_len:
                return False
        for entry in self._drain:
            # a slot admitted THIS round (its prefill entry is still
            # queued, so it is not in the mirror yet) is device-live
            # at pos = len(seq) — it rides the chunk write like every
            # slot and needs the same room
            if entry[0] == "prefill" and not entry[1].done \
                    and len(entry[1].seq) + K + 2 > self.max_len:
                return False
        if not parts:
            return False
        drafts = np.zeros((S, K), np.int32)
        dlen = np.zeros((S,), np.int32)
        if self.spec_draft == "ngram":
            for s, req, k_s in parts:
                dr = self._drafters.get(req.id)
                prop = dr.propose(k_s) if dr is not None else []
                if prop:
                    drafts[s, :len(prop)] = prop
                    dlen[s] = len(prop)
            if not dlen.any():
                return False
            _TM_SPEC_NGRAM.inc(int(dlen.sum()))
        else:
            self._model_drafts(parts, drafts, dlen)
            if not dlen.any():
                return False
            _TM_SPEC_MODEL.inc(int(dlen.sum()))
        ndraft = int(dlen.sum())
        self.stats["spec_drafted"] += ndraft
        _TM_SPEC_DRAFTED.inc(ndraft)
        tv0 = time.perf_counter()
        with tele.span("serving.verify_round", cat="serving",
                       slots_busy=busy, drafted=ndraft):
            self._caches, self._state, out = self._verify_fn(
                self._params, self._aux, self._caches,
                self._state, drafts, dlen)
        self._phase_add("dispatch", time.perf_counter() - tv0)
        if "verify" not in self._prog_seen:
            self._prog_seen.add("verify")
            profiler.register_program(
                "serving_verify", self._verify_fn,
                (self._params, self._aux, self._caches,
                 self._state, np.zeros((S, K), np.int32),
                 np.zeros((S,), np.int32)))
        self._drain.append(("verify", out, dlen))
        self.stats["steps"] += 1
        self.stats["spec_rounds"] += 1
        _TM_ROUNDS.inc()
        _TM_SPEC_ROUNDS.inc()
        _TM_SLOTS_BUSY.observe(busy)
        flt = _SERVING_FAULTS
        if flt is not None:
            flt.serving_crash()  # injected mid-round process death
        return True

    def _model_drafts(self, parts, drafts, dlen):
        """Draft-model proposals (``draft="model"``): catch the draft
        cache up on every token emitted since its last run (pending
        queues fed by ``_push_token``), then one greedy ``spec_k``-token
        proposal per slot — all in dispatches of the ONE draft
        program. Pending longer than the catch-up width (after
        fallback-round bursts) drains over several dispatches; only
        the last one's proposals are used. Slots with nothing pending
        ride along with an idempotent junk write above their head."""
        K = self.spec_k
        S = self.slots
        W = K + 1
        dd = self._draft_dec
        # each slot's proposal is taken from the dispatch in which its
        # catch-up COMPLETED: in a multi-dispatch drain (a fallback
        # burst longer than W), a slot that finished early would
        # otherwise ride later dispatches with a junk catch-up token
        # and have its valid proposal overwritten by noise
        final_props = np.zeros((S, K), np.int32)
        proposed = set()
        while True:
            pos = np.zeros((S,), np.int32)
            catchup = np.zeros((S, W), np.int32)
            clen = np.ones((S,), np.int32)
            again = False
            newly_done = []
            for s in range(S):
                pos[s] = min(self._draft_pos[s], self.max_len - W)
                pend = self._draft_pending[s]
                if pend:
                    n = min(len(pend), W)
                    catchup[s, :n] = pend[:n]
                    clen[s] = n
                    del pend[:n]
                    self._draft_pos[s] += n
                    if pend:
                        again = True
                    else:
                        newly_done.append(s)
            tdf0 = time.perf_counter()
            self._draft_caches, props = self._draft_fn(
                self._draft_params, self._draft_aux,
                self._draft_caches, pos, catchup, clen)
            self._phase_add("dispatch", time.perf_counter() - tdf0)
            if "draft" not in self._prog_seen:
                self._prog_seen.add("draft")
                profiler.register_program(
                    "serving_draft", self._draft_fn,
                    (self._draft_params, self._draft_aux,
                     self._draft_caches, pos, catchup, clen))
            if newly_done:
                props = np.asarray(props)                   # [S, K]
                for s in newly_done:
                    final_props[s] = props[s]
                    proposed.add(s)
            if not again:
                break
        for s, req, k_s in parts:
            if s in proposed:       # else: nothing pending fed the
                drafts[s, :k_s] = final_props[s, :k_s]  # draft — skip
                dlen[s] = k_s

    def step(self):
        """One scheduling round: retire cancelled/expired requests
        (round-boundary lifecycle sweep), advance every mid-prefill
        request by ONE chunk, admit staged requests into free slots
        (prefix copy + first prefill piece), dispatch ONE decode round
        (``steps_per_round`` fused all-slot steps) if any decodable
        slot is occupied, then drain output vectors that are
        ``drain_depth`` dispatches old (all of them once nothing is in
        flight). Returns the requests that finished since the last
        round — normal completions AND host retirements (check
        ``retire_reason``) — in completion order.

        Every non-idle round also lands a row in the bounded
        round-phase ledger (:meth:`round_table`, ``GET /rounds``) and
        feeds the ``serving.round_phase_ms.*`` histograms: the round's
        wall time decomposed into drain / prefix lookup / h2d staging /
        prefill / copy / decode-verify dispatch, with host scheduling
        as the exact remainder — the phases sum to the round wall time
        by construction (doc/observability.md "Round-phase
        attribution")."""
        self._check_open()
        rt0 = time.perf_counter()
        self._phase = {}
        dispatched = None
        try:
            if self._spec and self._drain:
                # speculation drains EAGERLY: drafting needs the
                # current context (the n-gram drafter and the
                # draft-model catch-up read drained tokens) and exact
                # per-slot positions; the tokens-per-dispatch the
                # verify step buys replaces the drain-lag pipelining
                # drain_depth bought (doc/serving.md)
                while self._drain:
                    self._drain_one()
            self._sweep()
            # chunked prefill, Sarathi-style per-round budget: at most
            # ~prefill_chunk tokens of prefill work run between decode
            # rounds — ONE piece of the oldest parked request, then
            # admissions' first pieces until the budget is spent
            # (_admit holds the overflow request for next round).
            # Resident decoders therefore stall at most one budget's
            # worth of prefill per round, however many long prompts
            # are in flight.
            self._round_budget = self.prefill_chunk or float("inf")
            if self._chunking:
                st = self._chunking.popleft()
                try:
                    if not self._advance_chunk(st):
                        self._chunking.append(st)
                except Exception as e:   # noqa: BLE001 — poisoned
                    self._poison(st, e)
            admitted = self._admit()
            busy = self.slots - len(self._free)
            _TM_OCCUPANCY.set(busy)
            if admitted or busy:
                # zero-admission rounds COUNT while work is resident
                # (they are what admission starvation looks like — the
                # histogram's 0 bucket exists for them); only
                # fully-idle polls are not a scheduling round
                _TM_ADMITTED.observe(admitted)
            # slots still mid-prefill have nothing to decode: a round
            # with ONLY those resident would be pure wasted dispatch.
            # Handoff-pinned slots likewise (their requests left), and
            # a prefill-role engine NEVER dispatches the decode family
            # — that is the role's compile contract
            if busy - len(self._chunking) - len(self._handoff_slots) > 0 \
                    and self.role != "prefill":
                if self._spec and self._spec_round(busy):
                    dispatched = "verify"
                else:
                    if self._spec:
                        # speculation armed but no slot had a usable
                        # draft (cold context, budget exhausted, or a
                        # slot too near the cache end for the chunk
                        # write): plain decode serves the round
                        _TM_SPEC_FALLBACK.inc()
                        self.stats["spec_fallback_rounds"] += 1
                    td0 = time.perf_counter()
                    with tele.span("serving.decode_round",
                                   cat="serving", slots_busy=busy):
                        self._caches, self._state, out = self._step_fn(
                            self._params, self._aux,
                            self._caches, self._state)
                    self._phase_add("dispatch",
                                    time.perf_counter() - td0)
                    dispatched = "decode"
                    if "decode" not in self._prog_seen:
                        self._prog_seen.add("decode")
                        profiler.register_program(
                            "serving_decode", self._step_fn,
                            (self._params, self._aux,
                             self._caches, self._state))
                    self._drain.append(("step", out))
                    self.stats["steps"] += 1
                    _TM_ROUNDS.inc()
                    _TM_SLOTS_BUSY.observe(busy)
                    flt = _SERVING_FAULTS
                    if flt is not None:
                        flt.serving_crash()   # injected process death
            # a prefill-role engine drains eagerly: no decode rounds
            # follow to push results out of the drain-lag window, and
            # every drained prefill is a handoff package the router is
            # waiting on
            while len(self._drain) > (
                    self._drain_depth
                    if self._busy() and self.role != "prefill" else 0):
                self._drain_one()
            self._last_ok_t = time.perf_counter()
            self._slo_tick(self._last_ok_t)
            self._record_round(rt0, busy, admitted, dispatched)
        finally:
            self._phase = None
        done_now, self._done_buf = self._done_buf, []
        return done_now

    def _record_round(self, rt0, busy, admitted, dispatched):
        """Land the finished round in the phase ledger + histograms.
        Pure-idle polls (nothing resident, admitted, or drained) are
        not scheduling rounds and are skipped; an aborted round (a
        watchdog trip unwinding step()) records nothing — its drain
        retries next round."""
        acc = self._phase
        wall = time.perf_counter() - rt0
        if not (admitted or busy or acc):
            return
        # host scheduling = the unattributed remainder (sweep, queue
        # bookkeeping, chunk math, drafter proposals). The attributed
        # phases are disjoint same-thread intervals inside
        # [rt0, now], so the remainder is >= 0 up to float error —
        # clamped, and the phases sum to wall_ms exactly.
        acc["sched"] = max(0.0, wall - sum(acc.values()))
        phases_ms = {k: round(v * 1e3, 4) for k, v in acc.items()}
        for k, v in phases_ms.items():
            _TM_PHASE[k].observe(v)
        _TM_ROUND_WALL.observe(wall * 1e3)
        self._round_no += 1
        self._rounds.append({
            "round": self._round_no,
            "t_s": round(rt0 - self._t0, 4),
            "wall_ms": round(wall * 1e3, 4),
            "slots_busy": busy,
            "admitted": admitted,
            "dispatched": dispatched,
            "phases_ms": phases_ms,
        })

    def round_table(self, n=None):
        """The last ``n`` (default: all retained, bounded at 256)
        round-phase ledger rows, oldest first — what ``GET /rounds``
        serves. Plain dicts: round number, start time (s since engine
        construction), wall ms, occupancy, admissions, which program
        the round dispatched (``decode``/``verify``/None), and the
        per-phase ms decomposition (summing to ``wall_ms``)."""
        # exposition-server threads read while the engine thread
        # appends; deque APPEND is atomic but ITERATION over a
        # mutating deque raises RuntimeError — retry instead of
        # holding a lock on the per-round hot path (the window is one
        # append; a scrape must never silently drop the engine)
        for _ in range(8):
            try:
                rows = list(self._rounds)
                break
            except RuntimeError:
                continue
        else:
            rows = []
        if n is not None:
            n = max(0, int(n))
            rows = rows[-n:] if n else []
        return [dict(r, phases_ms=dict(r["phases_ms"])) for r in rows]

    # -- observability plane (doc/observability.md) ---------------------
    def _slo_tick(self, now=None):
        """Refresh the multi-window SLO burn gauges from the TTFT /
        cadence histograms (rate-limited inside ``tele.SloWindow`` —
        per-round calls cost a dict lookup). Called at the end of
        every ``step()`` and by the exposition server per scrape, so
        the gauges stay current even when the engine idles. The
        histograms are process-wide: with several engines in one
        process the burn gauges reflect the engine that ticked last
        (deploy one engine per process for per-replica SLOs)."""
        for kind, thr, hist, windows in (
                ("ttft", self.slo_ttft_ms, _TM_TTFT_MS,
                 _SLO_TTFT_WINDOWS),
                ("cadence", self.slo_cadence_ms, _TM_CADENCE_MS,
                 _SLO_CADENCE_WINDOWS)):
            if thr is None:
                continue
            w = self._slo_windows.get(kind)
            if w is None or w.threshold != float(thr):
                # (re)build on first use or a threshold change — the
                # window history restarts, which is the honest reading
                # of "the SLO target changed"
                w = tele.SloWindow(
                    hist, thr, target=self.slo_target,
                    windows=[(s, g) for s, g in windows])
                self._slo_windows[kind] = w
            w.tick(now)

    def health(self):
        """Liveness summary for ``/healthz`` (plain dict, host-side):
        ``stuck`` is the PR 7 watchdog state — True from a
        ``round_timeout_ms`` trip until a later drain succeeds (the
        recovered device clears it); ``closed`` after :meth:`close`.
        ``last_round_age_s`` is how long since a ``step()`` completed
        — a serving loop that stopped stepping shows up here even
        without a watchdog armed."""
        now = time.perf_counter()
        return {
            "closed": self._closed,
            "stuck": self._watchdog_stuck_t is not None,
            "draining": self.draining,
            "role": self.role,
            "watchdog_trips": self.stats["watchdog_trips"],
            "slots": self.slots,
            "slots_busy": self.slots - len(self._free),
            "queued": self.queued(),
            "handoffs_waiting": len(self._handoff_out),
            "last_round_age_s": round(now - self._last_ok_t, 3),
        }

    def request_table(self):
        """Live + recently-retired request rows for ``/requests``:
        every unfinished request (queued, staged, mid-prefill, or
        decoding) followed by the flight recorder's retired ring.
        Plain dicts, host bookkeeping only."""
        now = time.perf_counter()
        rows = []
        for req in list(self._active.values()):
            if req.done:
                continue
            state = "queued" if req.t_admit is None else "running"
            rows.append({
                "id": req.id, "state": state,
                "prompt_len": int(len(req.prompt)),
                "tokens": len(req.tokens),
                "age_s": round(now - req.t_submit, 3),
                "deadline_ms": req.deadline_ms,
                "prefix_hit_tokens": req.prefix_hit_tokens,
            })
        rows.extend(self.flight.rows())
        # multi-replica processes expose every engine's table on ONE
        # /requests endpoint — rows are indistinguishable without the
        # owning engine's identity and role
        for row in rows:
            row["engine_id"] = self.engine_id
            row["role"] = self.role
        return rows

    def serve_forever(self, requests=None):
        """Drive the loop to completion: pull submissions from
        ``requests`` (optional iterable — dict kwargs for
        :meth:`submit`, a ``(prompt, kwargs)`` pair, a bare prompt
        array, or ``None`` meaning "nothing has arrived yet", which
        lets a generator pace an online arrival process), stepping
        continuously; between pulls the engine keeps serving whatever
        is resident. Returns all finished requests in completion order
        (host retirements included — check ``retire_reason``). With
        ``requests=None`` it serves what was already submitted and
        returns when idle.

        Failure containment: if the ``requests`` iterable (or a submit
        it drives) raises mid-iteration, already-admitted work FINISHES
        first — queued-but-unadmitted requests finish too under
        ``overload="block"``, or are shed under a shedding policy —
        and only then does the original exception propagate, traceback
        intact. On KeyboardInterrupt the engine :meth:`close`\\ s
        (pending requests fail with :class:`EngineClosed`) before the
        interrupt propagates."""
        self._check_open()
        completed = []
        src = iter(requests) if requests is not None else None
        exhausted = src is None
        ingest_error = None
        try:
            while True:
                # ingest until backpressure or a pacing None — one item
                # per round would starve free slots while the source
                # has ready requests
                while not exhausted and self.queued() < self.max_queue:
                    try:
                        item = next(src)
                    except StopIteration:
                        exhausted = True
                        break
                    except Exception as e:   # noqa: BLE001
                        ingest_error = e
                        break
                    try:
                        if item is None:
                            break          # nothing ready yet: decode
                        if isinstance(item, dict):
                            self.submit(**item)
                        elif isinstance(item, tuple) \
                                and len(item) == 2 \
                                and isinstance(item[1], dict):
                            self.submit(item[0], **item[1])
                        else:
                            self.submit(item, max_tokens=self.max_len)
                    except Exception as e:   # noqa: BLE001
                        ingest_error = e
                        break
                if ingest_error is not None and not exhausted:
                    # stop ingesting; shed the unadmitted backlog when
                    # the policy allows, then drain what was admitted
                    exhausted = True
                    if self.overload != "block":
                        why = ("with the unadmitted backlog after the "
                               "request stream raised (overload=%r "
                               "drops instead of draining it)"
                               % self.overload)
                        while self._shed_oldest(why):
                            pass
                completed.extend(self.step())
                if exhausted and self.idle:
                    break
            if ingest_error is not None:
                raise ingest_error
            return completed
        except KeyboardInterrupt:
            self.close()
            raise

    # -- shutdown -------------------------------------------------------
    def close(self):
        """Shut the engine down: every pending request — queued,
        staged, mid-prefill, or decoding — fails with a typed
        :class:`EngineClosed` error (``retire_reason="closed"``,
        already-drained tokens stay readable on ``.tokens``), the
        prompt stager stops, and every slot and prefix-cache pin is
        released. Idempotent; ``submit``/``step``/``serve_forever``
        raise :class:`EngineClosed` afterwards. Also usable as a
        context manager (``with engine: ...`` closes on exit), and
        installed by ``serve_forever`` on KeyboardInterrupt."""
        if self._closed:
            return
        self._closed = True
        # a closed engine is not "stuck": the wedged round died with
        # it, and /healthz must not 503 a process that closed the
        # tripped engine and replaced it with a healthy one
        self._watchdog_stuck_t = None
        for req in list(self._active.values()):
            self._retire_active(req, "closed", EngineClosed(
                "InferenceEngine: engine closed while request %r was "
                "pending" % (req.id,)))
        self._pending.clear()
        self._chunking.clear()
        self._held = None
        self._drain.clear()
        # outbound handoff packages die with the engine: mark them
        # resolved so a router holding one cannot release the slot of
        # (or deliver rows from) a closed engine, and free the pinned
        # slots directly
        while self._handoff_out:
            self._handoff_out.popleft().resolved = True
        for slot in sorted(self._handoff_slots):
            self._release_slot(slot)
        self._handoff_slots.clear()
        self._stager.close()
        self.capture.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.close()
        return False

    # -- crash-safe restart ---------------------------------------------
    def snapshot(self):
        """Host scheduler state as a plain JSON-serializable dict:
        every unfinished request (queued AND in-flight) with its
        prompt, the tokens drained so far, its sampling identity
        (seed/temperature — draws are keyed ``fold_in(seed, position)``,
        so a resumed request reproduces them), and its remaining
        deadline budget, plus the engine geometry. NO device state:
        prompt K/V is a pure function of the token ids, so
        :meth:`restore` re-prefills ``prompt + emitted`` (through the
        prefix cache where it hits) and every greedy continuation is
        byte-identical to the uninterrupted run. Valid after a crashed
        ``step()`` or a watchdog trip — tokens dispatched but never
        drained are simply re-generated."""
        now = time.perf_counter()
        reqs = []
        for req in self._active.values():
            if req.done:
                continue
            reqs.append({
                "id": req.id,
                "prompt": np.asarray(req.prompt).tolist(),
                "tokens": list(req.tokens),
                "max_tokens": int(req.max_tokens),
                "eos_id": req.eos_id,
                "temperature": float(req.temperature),
                "seed": int(req.seed),
                "deadline_ms": None if req._deadline is None
                else (req._deadline - now) * 1e3,
                "ttft_deadline_ms": None
                if req._ttft_deadline is None or req.t_first is not None
                else (req._ttft_deadline - now) * 1e3,
            })
        # packaged-but-undelivered handoffs: locally retired, but the
        # work is NOT done — a restore (or the fleet failover path)
        # re-prefills prompt + the already-emitted first token and
        # serves the remainder unified, byte-identically
        for pkg in self._handoff_out:
            if pkg.resolved:
                continue
            reqs.append({
                "id": pkg.id,
                "prompt": pkg.prompt.tolist(),
                "tokens": list(pkg.tokens),
                "max_tokens": int(pkg.max_tokens),
                "eos_id": pkg.eos_id,
                "temperature": float(pkg.temperature),
                "seed": int(pkg.seed),
                "deadline_ms": None,
                "ttft_deadline_ms": None,
            })
        return {
            "version": 1,
            "auto_seed": self._auto_seed,
            # provenance, NOT geometry: restore() gives the successor
            # a fresh identity and records this id as migrated_from
            "engine_id": self.engine_id,
            "engine": self._geometry(),
            "requests": reqs,
        }

    def _geometry(self):
        """Engine geometry as plain JSON — every constructor knob a
        fresh engine needs to serve the same way. Shared by
        :meth:`snapshot` (restore() feeds it back) and the traffic
        capture's header (``tools/replay_serving.py`` rebuilds from
        it). ``capture_dir`` rides along for the crash cycle
        (None inside the capture header itself — it is written before
        the knob resolves, and replay must not re-capture by
        default)."""
        return {
            "slots": self.slots,
            "prefill_buckets": list(self.prefill_buckets),
            "max_queue": self.max_queue,
            "stage_depth": self.stage_depth,
            "drain_depth": self._drain_depth,
            "steps_per_round": self.steps_per_round,
            "prefix_cache_mb": self.prefix_cache_mb,
            "prefill_chunk": self.prefill_chunk,
            "overload": self.overload,
            "round_timeout_ms": self.round_timeout_ms,
            "slo_ttft_ms": self.slo_ttft_ms,
            "slo_cadence_ms": self.slo_cadence_ms,
            "slo_target": self.slo_target,
            "flight_recorder": self.flight.retain,
            "spec_k": self.spec_k,
            "draft": self.spec_draft,
            "attn_impl": self.attn_impl,
            "tp": self.tp,
            "ep": self.ep,
            "weight_dtype": self.weight_dtype,
            "weight_group": self.weight_group,
            "matmul_impl": self.matmul_impl,
            "role": self.role,
            "handoff_dtype": self.handoff_dtype,
            "capture_dir": getattr(self, "capture_dir", None),
        }

    @classmethod
    def restore(cls, snap, decoder, **overrides):
        """Warm restart from :meth:`snapshot`: builds a fresh engine
        (same geometry unless ``overrides`` change it) on ``decoder``
        (the same weights) and resubmits every unfinished request,
        re-prefilling ``prompt + already-emitted`` so each one resumes
        exactly where it stopped — greedy continuations are
        byte-identical to an uninterrupted run, and sampled draws stay
        position-keyed. Emitted tokens reappear on the handles'
        ``.tokens``; resumed sequences longer than the largest bucket
        admit in bucket-sized pieces automatically. Remaining deadline
        budgets carry over (an already-expired one retires on the
        first round). Returns ``(engine, {request_id: Request})``.

        Speculation knobs (``spec_k``/``draft``) restore with the
        geometry; drafter context rebuilds from each request's
        ``prompt + emitted`` at admission, so accept rates warm back
        up immediately. A ``draft="model"`` snapshot needs the draft
        model back: pass ``draft_decoder=...`` in ``overrides`` (the
        snapshot is plain JSON and cannot carry weights)."""
        if not isinstance(snap, dict) or snap.get("version") != 1:
            raise MXNetError(
                "InferenceEngine.restore: not an engine snapshot "
                "(want the dict snapshot() returned)")
        cfg = dict(snap["engine"])
        cfg["prefill_buckets"] = tuple(cfg["prefill_buckets"])
        cfg.update(overrides)
        # migration provenance: the successor's capture header names
        # the donor engine, so a replayed crash/drain cycle attributes
        # each request to the replica lineage that finished it
        cfg.setdefault("migrated_from", snap.get("engine_id"))
        eng = cls(decoder, **cfg)
        handles = {}
        real_max_queue = eng.max_queue
        # resubmission must never shed: the crashed engine had already
        # accepted this work (its in-flight slots don't count as queue)
        eng.max_queue = max(real_max_queue, len(snap["requests"]))
        try:
            next_id = eng._next_id
            for r in snap["requests"]:
                req = eng.submit(
                    np.asarray(r["prompt"], np.int32),
                    max_tokens=r["max_tokens"], eos_id=r["eos_id"],
                    temperature=r["temperature"], seed=r["seed"],
                    request_id=r["id"],
                    deadline_ms=r.get("deadline_ms"),
                    ttft_deadline_ms=r.get("ttft_deadline_ms"),
                    _resume_tokens=r["tokens"])
                handles[req.id] = req
                if isinstance(req.id, int):
                    next_id = max(next_id, req.id + 1)
            eng._next_id = next_id   # fresh auto-ids never collide
            # likewise fresh auto-drawn seeds: resubmission passes
            # explicit seeds, so the new counter sits at 0 and the
            # next seed-less sampled submit would replay a resumed
            # request's draws
            eng._auto_seed = max(int(snap.get("auto_seed", 0)),
                                 *(int(r["seed"]) + 1
                                   for r in snap["requests"]), 0)
        finally:
            eng.max_queue = real_max_queue
        eng.stats["restores"] = 1
        _TM_RESTORES.inc()
        return eng, handles
