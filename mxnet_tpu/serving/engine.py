"""Continuous-batching inference engine over a slot-paged KV cache.

Architecture (doc/serving.md has the full story):

* ONE persistent KV cache of ``S`` slots x ``max_len`` — ``Decoder``'s
  own cache layouts (plain float, int8-quantized scales, sliding-window
  rings) with the batch axis reinterpreted as a SLOT axis. A request
  occupies one slot from admission to retirement; a freed slot is
  recycled without touching the others (stale rows are hidden by the
  ``key_pos <= pos`` causal mask until overwritten; window rings get
  their position buffers reset at admission).

* THREE compiled program families serve any request mix, ever:

  - **bucketed prefill** (one program per power-of-2 length bucket):
    a prompt CHUNK padded to its bucket is pushed through the derived
    incremental graph at positions ``[start, start + C)`` of its
    assigned slot — slot index, start position, true chunk length,
    finality, temperature, rng key, eos id and token budget are all
    traced operands. The FINAL chunk samples the first output token
    in-program at the last real prompt position and scatter-updates
    the per-slot state vectors; non-final chunks (``prefill_chunk``
    pieces of a long prompt, interleaved with decode rounds —
    Sarathi-Serve, Agrawal et al. 2024) only write K/V and park the
    slot in a frozen state whose idempotent decode-round rewrite is
    harmless. Admission costs zero extra compiled programs.
  - **fused decode step** (exactly one program): one token for EVERY
    slot at its own position — per-slot position vector, per-slot
    temperature/rng sampling, vectorized EOS/length masking. Finished
    slots freeze (their write is idempotent) until reused.
  - **bucketed prefix copy** (one program per bucket, when the prefix
    cache is on): rows ``[0, B)`` of one cache slot land in another in
    a single compiled slice+scatter — pool→slot on a prefix hit
    (the matched prompt prefix's K/V replaces its prefill FLOPs,
    RadixAttention-style — Zheng et al. 2023), slot→pool when a
    freshly prefilled prompt is retained. Source/destination slot and
    direction are traced operands.

* a host-side **prefix cache** (``serving/prefix.py``): a refcounted-
  LRU trie over token ids maps a new prompt to the longest prefix a
  RETAINED prompt shares with it; retained prompts own slots in a
  reserved on-device pool (same cache layout, extra slot axis rows)
  bounded by ``prefix_cache_mb``. Windowed-ring models bypass it —
  ring eviction invalidates absolute-position reuse (doc/serving.md).

* a host-side scheduler that admits queued requests into freed slots
  BETWEEN device steps (iteration-level / continuous batching — Orca,
  OSDI '22), retires finished sequences, and overlaps host work with
  device execution twice over: prompt h2d staging rides the unified
  depth-k ``io.StagedStream`` helper (PR 2's machinery), and output
  token vectors are drained ``drain_depth`` dispatches behind the
  device, so the step stream never blocks on either edge.

Determinism guarantees (pinned by tests/test_serving.py): greedy
(``temperature=0``) outputs are byte-identical to offline
``Decoder.generate`` per request, regardless of admission order, slot
assignment, co-resident requests, or bucket padding; sampled outputs
depend only on ``(seed, position)`` — not on scheduling.
"""
from __future__ import annotations

import collections
import os
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .. import telemetry as tele
from ..io import StagedStream
from ..parallel.decode import Decoder
from .prefix import PrefixCache

__all__ = ["InferenceEngine", "Request"]

# hard bound on reserved prefix-pool slots: the byte budget is the
# real knob; this only stops a tiny model + big budget from minting a
# silly slot axis (256 entries is far past any workload's useful
# distinct-prefix count)
_MAX_POOL_SLOTS = 256

# per-request serving stats (doc/observability.md "serving"): all
# host-side perf_counter arithmetic on values the scheduler already
# tracks — nothing new crosses the device boundary
_TM_QUEUE_WAIT_MS = tele.histogram("serving.queue_wait_ms")
_TM_TTFT_MS = tele.histogram("serving.ttft_ms")
_TM_CADENCE_MS = tele.histogram("serving.token_cadence_ms")
_TM_TOKENS = tele.counter("serving.tokens")
_TM_COMPLETED = tele.counter("serving.completed")
_TM_RETIRED_EOS = tele.counter("serving.retired_eos")
_TM_RETIRED_LENGTH = tele.counter("serving.retired_length")
_TM_ROUNDS = tele.counter("serving.rounds")
_TM_PREFILLS = tele.counter("serving.prefills")
_TM_ADMITTED = tele.histogram(
    "serving.admitted_per_round", buckets=(0, 1, 2, 4, 8, 16, 32, 64))
_TM_SLOTS_BUSY = tele.histogram(
    "serving.slots_busy_per_round",
    buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128))
_TM_OCCUPANCY = tele.gauge("serving.slot_occupancy")
# prefix cache + chunked prefill (all host-side: the lookup is a trie
# walk, the copy/chunk spans time dispatches — nothing crosses the
# device boundary beyond the programs themselves)
_TM_PREFIX_HITS = tele.counter("serving.prefix_hits")
_TM_PREFIX_MISSES = tele.counter("serving.prefix_misses")
_TM_PREFIX_HIT_TOKENS = tele.counter("serving.prefix_hit_tokens")
_TM_PREFIX_LOOKUP_MS = tele.histogram(
    "serving.prefix_lookup_ms",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0))
_TM_PREFIX_BYTES = tele.gauge("serving.prefix_cache_bytes")
_TM_PREFIX_EVICTIONS = tele.counter("serving.prefix_evictions")
_TM_PREFIX_INSERT_SKIPPED = tele.counter(
    "serving.prefix_insert_skipped")
_TM_CHUNKS = tele.histogram(
    "serving.prefill_chunks_per_request",
    buckets=(1, 2, 4, 8, 16, 32, 64))
# compile_counts re-exported as telemetry: the in-engine log stays the
# tested contract; these make recompiles visible in ONE snapshot next
# to everything else
_TM_COMPILE_DECODE = tele.counter("serving.compiles_decode")
_TM_COMPILE_PREFILL = tele.counter("serving.compiles_prefill")
_TM_COMPILE_COPY = tele.counter("serving.compiles_copy")


class Request:
    """One submitted generation request (handle returned by
    :meth:`InferenceEngine.submit`).

    ``tokens`` fills in as output drains: generated ids only (no
    prompt echo), including ``eos_id`` when hit. ``done`` flips when
    the sequence retires; ``result()`` returns the tokens as int32
    numpy. Latency probes: ``t_submit``/``t_admit``/``t_first``/
    ``t_done`` (perf_counter seconds; admit = slot assigned + prefill
    dispatched; first = first token DRAINED, i.e. visible to the
    caller, not merely computed). ``retire_reason`` is ``"eos"`` or
    ``"length"`` once done. ``prefix_hit_tokens`` counts prompt
    positions whose K/V came from the prefix cache instead of prefill
    FLOPs; ``prefill_chunks`` how many prefill dispatches admitted the
    prompt (1 unless ``prefill_chunk`` split it). The same breakdown
    feeds the ``serving.*`` telemetry histograms (queue wait / TTFT /
    per-token cadence / prefix + chunk stats — doc/observability.md).
    """

    def __init__(self, rid, prompt, max_tokens, eos_id, temperature,
                 seed, limit):
        self.id = rid
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.eos_id = eos_id
        self.temperature = temperature
        self.seed = seed
        self.limit = limit          # min(max_tokens, max_len - P)
        self.tokens = []
        self.done = False
        self.t_submit = time.perf_counter()
        self.t_admit = None
        self.t_first = None
        self.t_done = None
        self.retire_reason = None
        self.prefix_hit_tokens = 0
        self.prefill_chunks = 0

    def result(self):
        if not self.done:
            raise MXNetError("request %s is not finished" % self.id)
        return np.asarray(self.tokens, np.int32)

    def __repr__(self):
        return ("Request(id=%r, prompt_len=%d, max_tokens=%d, done=%s, "
                "generated=%d)" % (self.id, len(self.prompt),
                                   self.max_tokens, self.done,
                                   len(self.tokens)))


class _PendingSource:
    """StagedStream source over the engine's pending deque (empty deque
    = StopIteration; the stream runs ``live_source`` mode, so submits
    arriving later are staged by the very next fill)."""

    def __init__(self, dq):
        self._dq = dq

    def next(self):
        if not self._dq:
            raise StopIteration
        return self._dq.popleft()

    def reset(self):
        pass


def _default_buckets(max_len):
    out, b = [], 16
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


def _raw_key(seed):
    """threefry PRNGKey layout without dispatching a device op (the
    compile-count contract stays clean): [hi32, lo32] of the seed."""
    seed = int(seed) & 0xFFFFFFFFFFFFFFFF
    return np.array([seed >> 32, seed & 0xFFFFFFFF], np.uint32)


class InferenceEngine:
    """Continuous-batching serving loop over a :class:`Decoder`.

    Parameters
    ----------
    decoder : Decoder
        The derived incremental program (any cache flavor: bf16/int8
        ``cache_dtype``, sliding-window models, GQA, rope). Build one
        with ``Decoder(symbol, params, max_len=...)`` or use
        :meth:`from_checkpoint` / ``FeedForward.as_serving_engine``.
        ``cache_block`` prefix-bounded reads are not supported under
        slot addressing (each slot has its own clock) — construct the
        decoder with ``cache_block=None`` (the engine refuses
        otherwise rather than silently decoding differently).
    slots : int
        ``S``, the resident-sequence capacity — the continuous batch
        size and the cache's slot-axis length. Throughput knob: decode
        cost per step is roughly flat until the chip saturates, so
        more slots = more tokens per step (tools/bench_serving.py
        sweeps it).
    prefill_buckets : tuple of int, optional
        Ascending prompt-padding lengths; a prompt takes the smallest
        bucket >= its length (default: powers of two from 16, capped
        at ``max_len``). One prefill program compiles per bucket
        actually used — the whole compile budget is
        ``len(buckets) + 1``.
    max_queue : int
        Backpressure bound on submitted-but-not-admitted requests;
        ``submit`` raises ``MXNetError`` beyond it.
    stage_depth : int
        Depth of the prompt h2d stager (``io.StagedStream``).
    drain_depth : int
        How many step outputs may remain un-drained while work is in
        flight — the d2h analogue of ``stage_depth``. Retirement is
        discovered at drain time, so a slot frees at most
        ``drain_depth`` rounds after its sequence finished (the device
        freezes finished slots in the meantime).
    steps_per_round : int
        Tokens decoded per dispatched round: the decode program is a
        ``lax.scan`` of this many fused all-slots steps, amortizing
        the per-dispatch host/relay overhead k-fold (one jit call,
        one [k, S] output drain per k tokens). Admission/retirement
        granularity coarsens to k tokens — a slot freed mid-round sits
        frozen until the round ends, so k should stay well under the
        typical output length (k=1 is latency-optimal per-token
        scheduling; the chip-facing bench uses 8). Still ONE compiled
        decode program either way.
    prefix_cache_mb : float, optional
        Byte budget (MiB) for the prefix-reuse pool: prompts are
        retained as on-device K/V rows in a reserved slot pool, and a
        new request whose prompt shares a prefix with a retained one
        gets that prefix COPIED into its slot (one compiled copy per
        bucket) instead of re-prefilled — shared system prompts stop
        paying their FLOPs per request. Default: the
        ``MXNET_SERVING_PREFIX_CACHE_MB`` env var, else 64. ``0``
        disables. Pool slots = budget // per-slot cache bytes (capped
        at 256); eviction is refcounted LRU. Windowed-ring decoders
        bypass the cache automatically (ring eviction invalidates
        absolute-position reuse — doc/serving.md). Greedy outputs stay
        byte-identical with the cache on or off.
    prefill_chunk : int, optional
        Chunked-prefill bound: a prompt (suffix) longer than this many
        tokens is admitted as a SEQUENCE of chunk-sized prefill
        dispatches interleaved with decode rounds, under a per-round
        prefill budget of one chunk shared by all in-flight admissions
        — resident decode slots stall ~one chunk of prefill work per
        round, not one whole prompt (nor a burst of them): the p99
        token-cadence lever under long-prompt traffic. Also lifts the
        submit length cap from the largest bucket to ``max_len - 1``
        (pieces only need the chunk to fit a bucket). Default: the
        ``MXNET_SERVING_PREFILL_CHUNK`` env var, else 0 (= monolithic
        prefill). Uses the SAME bucketed prefill programs (chunk start
        is a traced operand); greedy outputs stay byte-identical
        across any chunk boundary.
    """

    def __init__(self, decoder, slots=8, prefill_buckets=None,
                 max_queue=256, stage_depth=2, drain_depth=2,
                 steps_per_round=1, prefix_cache_mb=None,
                 prefill_chunk=None):
        if not isinstance(decoder, Decoder):
            raise MXNetError("InferenceEngine needs a Decoder, got %r"
                             % type(decoder).__name__)
        if decoder._cache_block is not None:
            raise MXNetError(
                "InferenceEngine: slot-paged decoding does not support "
                "cache_block prefix-bounded reads (per-slot positions); "
                "build the Decoder with cache_block=None")
        self._dec = decoder
        self.max_len = decoder.max_len
        self.slots = int(slots)
        if self.slots < 1:
            raise MXNetError("InferenceEngine: slots must be >= 1")
        if prefill_buckets is None:
            prefill_buckets = _default_buckets(self.max_len)
        buckets = tuple(int(b) for b in prefill_buckets)
        if not buckets or list(buckets) != sorted(set(buckets)) \
                or buckets[0] < 1 or buckets[-1] > self.max_len:
            raise MXNetError(
                "InferenceEngine: prefill_buckets must be strictly "
                "ascending lengths in [1, max_len], got %r" % (buckets,))
        self.prefill_buckets = buckets
        self.max_queue = int(max_queue)
        self._drain_depth = max(0, int(drain_depth))
        self.steps_per_round = int(steps_per_round)
        if self.steps_per_round < 1:
            raise MXNetError("InferenceEngine: steps_per_round must "
                             "be >= 1")
        if prefill_chunk is None:
            prefill_chunk = int(os.environ.get(
                "MXNET_SERVING_PREFILL_CHUNK", "0") or 0)
        self.prefill_chunk = int(prefill_chunk)
        if self.prefill_chunk < 0:
            raise MXNetError("InferenceEngine: prefill_chunk must be "
                             ">= 0 (0 disables chunking)")
        if self.prefill_chunk > buckets[-1]:
            raise MXNetError(
                "InferenceEngine: prefill_chunk=%d exceeds the largest "
                "prefill bucket %d — every chunk piece must fit a "
                "bucket program" % (self.prefill_chunk, buckets[-1]))

        # device-resident: the slot-paged cache + per-slot state vectors
        S = self.slots
        self._caches = decoder.init_cache(S)
        self._state = (
            jnp.zeros((S,), jnp.int32),        # pos: next write position
            jnp.zeros((S,), jnp.int32),        # tok: last sampled token
            jnp.zeros((S,), bool),             # live
            jnp.zeros((S,), jnp.float32),      # temperature
            jnp.zeros((S, 2), jnp.uint32),     # rng key
            jnp.full((S,), -1, jnp.int32),     # eos id (-1: none)
            jnp.zeros((S,), jnp.int32),        # last allowed position
        )

        # prefix-reuse pool: a SEPARATE cache tree of pool slots (same
        # per-slot layout) holding retained prompt K/V. Separate, not
        # extra rows on the serving tree, so the fused decode step
        # keeps vmapping over exactly S lanes — pool size must never
        # tax the per-token path.
        if prefix_cache_mb is None:
            prefix_cache_mb = float(os.environ.get(
                "MXNET_SERVING_PREFIX_CACHE_MB") or "64")
        self.prefix_cache_mb = float(prefix_cache_mb)
        if self.prefix_cache_mb < 0:
            raise MXNetError("InferenceEngine: prefix_cache_mb must "
                             "be >= 0 (0 disables the prefix cache)")
        self._windowed = any(decoder._node_window(n)
                             for n in decoder._mha)
        slot_bytes = sum(x.nbytes for x in
                         jax.tree_util.tree_leaves(self._caches)) // S
        pool_slots = 0
        if self.prefix_cache_mb > 0 and not self._windowed:
            pool_slots = min(
                int(self.prefix_cache_mb * 2**20) // max(1, slot_bytes),
                _MAX_POOL_SLOTS)
        if pool_slots > 0:
            self._pool = decoder.init_cache(pool_slots)
            self._prefix = PrefixCache(pool_slots, slot_bytes)
        else:
            self._pool = None
            self._prefix = None

        # host-side scheduler state
        self._pending = collections.deque()
        self._stager = StagedStream(_PendingSource(self._pending),
                                    place=self._place_prompt,
                                    depth=stage_depth, live_source=True)
        self._free = collections.deque(range(S))  # FIFO slot recycling
        self._mirror = [None] * S   # drain-side view: slot -> Request
        self._drain = collections.deque()
        # requests admitted to a slot whose prompt is still being
        # chunk-prefilled, oldest first; plus one admission candidate
        # held over when a round's prefill budget ran out. Each round
        # runs at most ~prefill_chunk tokens of prefill work between
        # decode rounds (the chunked-prefill cadence bound)
        self._chunking = collections.deque()
        self._held = None
        self._round_budget = float("inf")
        self._next_id = 0
        self._auto_seed = 0
        self.stats = {"submitted": 0, "completed": 0, "prefills": 0,
                      "steps": 0, "tokens": 0, "prefix_hits": 0,
                      "prefix_hit_tokens": 0, "prefill_chunks": 0,
                      "prefix_copies": 0}

        # the three compiled program families; the log records one tag
        # per TRACE (python side effects run at trace time only), so it
        # IS the compile count — tests pin the contract against it
        self._compile_log = []
        on_chip = jax.default_backend() != "cpu"
        self._donate = (2, 3) if on_chip else ()
        self._copy_donate = (0, 1) if on_chip else ()
        self._step_fn = jax.jit(self._make_step(),
                                donate_argnums=self._donate)
        self._prefill_fns = {}
        self._copy_fns = {}

    # -- construction ---------------------------------------------------
    @classmethod
    def from_checkpoint(cls, prefix, epoch, max_len, slots=8,
                        prefill_buckets=None, max_queue=256,
                        stage_depth=2, drain_depth=2, steps_per_round=1,
                        prefix_cache_mb=None, prefill_chunk=None,
                        **decoder_kwargs):
        """Checkpoint → serving engine in one call
        (``prefix-symbol.json`` + ``prefix-NNNN.params``, the reference
        format): builds the :class:`Decoder` via
        ``Decoder.from_checkpoint`` and wraps it. ``decoder_kwargs``
        reach the decoder (``compute_dtype``, ``cache_dtype``, ...)."""
        decoder_kwargs.setdefault("cache_block", None)
        dec = Decoder.from_checkpoint(prefix, epoch, max_len,
                                      **decoder_kwargs)
        return cls(dec, slots=slots, prefill_buckets=prefill_buckets,
                   max_queue=max_queue, stage_depth=stage_depth,
                   drain_depth=drain_depth,
                   steps_per_round=steps_per_round,
                   prefix_cache_mb=prefix_cache_mb,
                   prefill_chunk=prefill_chunk)

    # -- compiled programs ----------------------------------------------
    def _make_step(self):
        dec = self._dec
        k_rounds = self.steps_per_round

        def one_step(caches, state, params, aux):
            pos, tok, live, temp, keys, eos, last = state
            # write each slot's pending token at ITS position, read
            # logits for the next one (frozen slots rewrite their last
            # token in place — idempotent)
            logits, caches = dec._run_slots(params, aux, caches, pos,
                                            tok[:, None])
            logits = logits[:, 0]
            nxt_pos = pos + 1
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

            def with_sampling(_):
                t = jnp.where(temp > 0.0, temp, jnp.float32(1.0))

                def draw(k, q, row):
                    return jax.random.categorical(
                        jax.random.fold_in(k, q), row)

                sampled = jax.vmap(draw)(
                    keys, nxt_pos,
                    logits.astype(jnp.float32) / t[:, None]
                ).astype(jnp.int32)
                return jnp.where(temp > 0.0, sampled, greedy)

            # all-greedy rounds (the common case) must not pay the
            # per-slot fold_in + categorical they will never take —
            # same reasoning as Decoder._build_generate's lax.cond
            nxt = lax.cond(jnp.any(temp > 0.0), with_sampling,
                           lambda _: greedy, None)
            done_now = (nxt == eos) | (nxt_pos >= last)
            out = jnp.where(live, nxt, -1)     # -1: slot had no token
            live2 = live & ~done_now
            pos2 = jnp.where(live, nxt_pos, pos)
            tok2 = jnp.where(live, nxt, tok)
            return caches, (pos2, tok2, live2, temp, keys, eos, last), \
                out

        def step(params, aux, caches, state):
            self._compile_log.append("decode")  # trace-time, see above
            _TM_COMPILE_DECODE.inc()

            def body(carry, _):
                caches, st = carry
                caches, st, out = one_step(caches, st, params, aux)
                return (caches, st), out

            (caches, state), outs = lax.scan(body, (caches, state),
                                             None, length=k_rounds)
            return caches, state, outs          # outs [k, S]

        return step

    def _prefill_fn(self, bucket):
        if bucket not in self._prefill_fns:
            dec = self._dec

            def prefill(params, aux, caches, state, slot, tokens,
                        start, true_len, final, temp, key, eos,
                        max_toks):
                # ONE program per bucket serves whole prompts AND every
                # chunk of a chunked prefill: start, the chunk's true
                # length and finality are traced operands. total = the
                # absolute prompt length covered so far.
                self._compile_log.append(("prefill", bucket))
                _TM_COMPILE_PREFILL.inc()
                pos, tok, live, temps, keys, eoss, lasts = state
                total = start + true_len
                sub = dec.slot_slice(caches, slot)
                # ring-position reset: a recycled slot must not leak
                # the previous occupant's window entries — but ONLY on
                # the first chunk; later chunks extend the same ring
                sub = dec.clear_window_positions(
                    sub, only_if=start == jnp.int32(0))
                # valid_len (absolute): pad rows must not enter window
                # rings (they would EVICT real in-window keys — linear
                # cache rows are masked-until-overwritten, ring slots
                # wrap)
                logits, sub = dec._run(params, aux, sub, start, tokens,
                                       valid_len=total)
                caches = dec.slot_update(caches, slot, sub)
                v = logits.shape[2]
                zero = jnp.int32(0)
                lastlog = lax.dynamic_slice(
                    logits, (zero, true_len - 1, zero), (1, 1, v))[0, 0]
                greedy = jnp.argmax(lastlog, -1).astype(jnp.int32)
                t = jnp.where(temp > 0.0, temp, jnp.float32(1.0))
                sampled = jax.random.categorical(
                    jax.random.fold_in(key, total),
                    lastlog.astype(jnp.float32) / t).astype(jnp.int32)
                t0 = jnp.where(temp > 0.0, sampled, greedy)
                lastp = jnp.minimum(total + max_toks - 1,
                                    dec.max_len - 1).astype(jnp.int32)
                done0 = (t0 == eos) | (total >= lastp)
                # a NON-final chunk parks the slot dead at (pos=total,
                # tok=last chunk token): the decode rounds that
                # interleave until the next chunk rewrite exactly that
                # token's K/V at row `total` — a row the next chunk
                # overwrites before any masked read could see it, the
                # same idempotent-freeze contract finished slots use
                lastchunk = lax.dynamic_slice(
                    tokens, (zero, true_len - 1), (1, 1))[0, 0]
                state2 = (pos.at[slot].set(total),
                          tok.at[slot].set(
                              jnp.where(final, t0, lastchunk)),
                          live.at[slot].set(final & ~done0),
                          temps.at[slot].set(temp),
                          keys.at[slot].set(key),
                          eoss.at[slot].set(eos),
                          lasts.at[slot].set(lastp))
                return caches, state2, t0

            self._prefill_fns[bucket] = jax.jit(
                prefill, donate_argnums=self._donate)
        return self._prefill_fns[bucket]

    def _copy_fn(self, bucket):
        """Compiled slot-to-slot prefix copy, one program per bucket:
        rows ``[0, bucket)`` of a source slot land in a destination
        slot. Source/destination may each be a serving slot or a pool
        slot — the direction booleans are traced operands, so ONE
        program covers pool→slot (prefix hit) and slot→pool
        (retention). int8 flavors copy their row scales alongside
        automatically (the copy is a tree-map over every cache
        buffer)."""
        if bucket not in self._copy_fns:
            def copy(serv, pool, src, dst, src_pool, dst_pool):
                self._compile_log.append(("copy", bucket))
                _TM_COMPILE_COPY.inc()
                rows = lax.cond(
                    src_pool,
                    lambda _: Decoder.slot_prefix_rows(pool, src,
                                                       bucket),
                    lambda _: Decoder.slot_prefix_rows(serv, src,
                                                       bucket),
                    None)
                serv = lax.cond(
                    dst_pool, lambda s: s,
                    lambda s: Decoder.slot_write_prefix_rows(s, dst,
                                                             rows),
                    serv)
                pool = lax.cond(
                    dst_pool,
                    lambda p: Decoder.slot_write_prefix_rows(p, dst,
                                                             rows),
                    lambda p: p, pool)
                return serv, pool

            self._copy_fns[bucket] = jax.jit(
                copy, donate_argnums=self._copy_donate)
        return self._copy_fns[bucket]

    def _dispatch_copy(self, length, src, dst, src_pool, dst_pool):
        """Bucket ``length`` and dispatch the copy program (prefix-hit
        admission or retention insert)."""
        bucket = self._bucket_for(length)
        with tele.span("serving.prefix_copy", cat="serving",
                       bucket=bucket, to_pool=bool(dst_pool)):
            self._caches, self._pool = self._copy_fn(bucket)(
                self._caches, self._pool, np.int32(src), np.int32(dst),
                np.bool_(src_pool), np.bool_(dst_pool))
        self.stats["prefix_copies"] += 1

    @property
    def compile_counts(self):
        """{'decode': n, 'prefill': {bucket: n}, 'copy': {bucket: n}}
        — the compile-count contract: after any workload, decode == 1,
        each USED prefill bucket == 1 and each USED copy bucket == 1
        (chunked prefill reuses the prefill buckets — chunk start is a
        traced operand, so chunking adds NO programs; one copy program
        covers both pool→slot and slot→pool). doc/serving.md."""
        out = {"decode": 0, "prefill": {}, "copy": {}}
        for tag in self._compile_log:
            if tag == "decode":
                out["decode"] += 1
            else:
                fam = out[tag[0]]
                fam[tag[1]] = fam.get(tag[1], 0) + 1
        return out

    # -- host scheduler -------------------------------------------------
    def _bucket_for(self, n):
        for b in self.prefill_buckets:
            if b >= n:
                return b
        raise MXNetError(
            "InferenceEngine: prompt length %d exceeds the largest "
            "prefill bucket %d" % (n, self.prefill_buckets[-1]))

    def _place_prompt(self, req):
        """Stager place fn: pad to the bucket and dispatch the h2d
        (async) — runs up to stage_depth requests ahead of admission.

        A prompt longer than ``prefill_chunk`` is guaranteed to admit
        as chunk pieces built at admission time (the split depends on
        the prefix match), so its full-prompt h2d would only be
        discarded — stage nothing. A prefix HIT on a short prompt also
        discards the staged array, but hits are unknowable this far
        ahead of admission; the waste there is one small int32 h2d
        (chunk/suffix arrays are a few KB — the prefill dispatch they
        feed dominates)."""
        p = len(req.prompt)
        if self.prefill_chunk and p > self.prefill_chunk:
            return req, None
        bucket = self._bucket_for(p)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :p] = req.prompt
        return req, jax.device_put(padded)

    def queued(self):
        """Requests submitted but not yet admitted to a slot."""
        return len(self._pending) + self._stager.staged() \
            + (self._held is not None)

    @property
    def idle(self):
        return not self._pending and self._stager.staged() == 0 \
            and self._held is None \
            and len(self._free) == self.slots and not self._drain \
            and not self._chunking

    def submit(self, prompt, max_tokens, eos_id=None, temperature=0.0,
               seed=None, request_id=None):
        """Queue one generation request; returns its :class:`Request`
        handle (fills in as the engine steps).

        prompt : 1-D int sequence, ``1 <= len <= max_len - 1`` (and
        within the largest bucket). ``max_tokens`` is truncated to the
        cache: at most ``max_len - len(prompt)`` tokens come back.
        ``eos_id``: generation stops after emitting it (included in
        the output). ``temperature=0``: greedy, byte-identical to
        ``Decoder.generate``; > 0 samples with ``seed`` (auto-drawn if
        omitted) — reproducible and schedule-independent.

        Raises ``MXNetError`` once ``max_queue`` requests are waiting
        (backpressure — callers drive :meth:`step` to drain).
        """
        if self.queued() >= self.max_queue:
            raise MXNetError(
                "InferenceEngine: request queue is full (%d waiting; "
                "max_queue=%d) — step() the engine to drain it"
                % (self.queued(), self.max_queue))
        # validate shape/dtype HERE, where the caller can see the
        # problem — a bad prompt forwarded to the compiled programs
        # surfaces as an opaque shape/dtype error rounds later
        try:
            prompt = np.asarray(prompt)
        except Exception as e:
            raise MXNetError(
                "InferenceEngine: prompt is not array-like (%s)" % e)
        if prompt.ndim != 1:
            raise MXNetError(
                "InferenceEngine: prompt must be a 1-D token sequence "
                "(one request per submit), got shape %r"
                % (prompt.shape,))
        if prompt.size < 1:
            raise MXNetError("InferenceEngine: empty prompt")
        if not np.issubdtype(prompt.dtype, np.integer):
            raise MXNetError(
                "InferenceEngine: prompt token ids must be integers, "
                "got dtype %s (floats would be silently truncated)"
                % prompt.dtype)
        prompt = prompt.astype(np.int32)
        if prompt.size > self.max_len - 1:
            raise MXNetError(
                "InferenceEngine: prompt length %d leaves no room to "
                "generate (max_len=%d)" % (prompt.size, self.max_len))
        if not self.prefill_chunk:
            # monolithic prefill must fit one bucket program; chunked
            # engines serve ANY prompt <= max_len - 1 in pieces (each
            # piece <= prefill_chunk <= the largest bucket)
            self._bucket_for(prompt.size)
        max_tokens = int(max_tokens)
        if max_tokens < 1:
            raise MXNetError("InferenceEngine: max_tokens must be >= 1")
        if seed is None:
            seed = self._auto_seed
            self._auto_seed += 1
        rid = request_id
        if rid is None:
            rid = self._next_id
            self._next_id += 1
        limit = min(max_tokens, self.max_len - prompt.size)
        req = Request(rid, prompt, max_tokens, eos_id,
                      float(temperature), seed, limit)
        self._pending.append(req)
        self.stats["submitted"] += 1
        return req

    def _admit(self):
        """Fill freed slots from the staged queue, between device
        steps (iteration-level scheduling). Admission = prefix-cache
        lookup (longest retained prefix → one compiled row copy into
        the slot) + the FIRST prefill piece of the uncovered suffix;
        further pieces run one budget's worth per round via the
        chunking queue. Under chunking, each admission's first piece
        draws from the round's prefill-token budget — a burst of
        arrivals admits only as much prefill work per round as the
        budget allows (the held request resumes first next round, so
        FIFO order is preserved). Returns how many requests were
        admitted."""
        admitted = 0
        while self._free:
            if self._held is not None:
                req, dev, self._held = \
                    self._held[0], self._held[1], None
            else:
                try:
                    req, dev = self._stager.next()
                except StopIteration:
                    break
            p = len(req.prompt)
            hit, entry, depth = 0, None, 0
            if self._prefix is not None:
                with tele.span("serving.prefix_lookup", cat="serving",
                               hist=_TM_PREFIX_LOOKUP_MS):
                    depth, entry = self._prefix.lookup(req.prompt)
                # a FULL hit still re-prefills the last prompt token:
                # the cache retains K/V only, and the first generated
                # token needs the last position's logits
                hit = min(depth, p - 1)
                # a hit only pays when it REDUCES prefill work (fewer
                # padded tokens across the piece split); otherwise the
                # copy dispatch is pure overhead on top of the same
                # bucket-quantized prefill — treat as miss
                if hit > 0 and self._suffix_cost(p - hit) \
                        >= self._suffix_cost(p):
                    hit, entry = 0, None
            first_piece = min(p - hit, self.prefill_chunk or p - hit)
            if first_piece > self._round_budget:
                # this round's prefill budget is spent: hold the
                # request (admitted next round, before newer arrivals)
                self._held = (req, dev)
                break
            slot = self._free.popleft()
            req.t_admit = time.perf_counter()
            _TM_QUEUE_WAIT_MS.observe(
                (req.t_admit - req.t_submit) * 1e3)
            if self._prefix is not None:
                if hit > 0:
                    self._prefix.acquire(entry)
                    req.prefix_hit_tokens = hit
                    self.stats["prefix_hits"] += 1
                    self.stats["prefix_hit_tokens"] += hit
                    _TM_PREFIX_HITS.inc()
                    _TM_PREFIX_HIT_TOKENS.inc(hit)
                    self._dispatch_copy(hit, src=entry.slot, dst=slot,
                                        src_pool=True, dst_pool=False)
                else:
                    entry = None    # unused match: nothing to release
                    _TM_PREFIX_MISSES.inc()
            st = {"req": req, "slot": slot, "dev": dev, "next": hit,
                  "entry": entry,
                  # retain only prompts no entry already covers whole
                  # (a second copy buys nothing) that fit the copy
                  # bucket family (longer chunked prompts stay
                  # unretained — their prefixes can still hit via
                  # shorter entries)
                  "insert": self._prefix is not None and depth < p
                  and p <= self.prefill_buckets[-1]}
            if not self._advance_chunk(st):
                self._chunking.append(st)
            admitted += 1
        return admitted

    def _suffix_cost(self, n):
        """Prefill-work proxy for an ``n``-token suffix: total PADDED
        tokens across its piece split — what bucket quantization
        actually charges for (piece count alone would demote every hit
        whose suffix and full prompt both fit one chunk)."""
        chunk = self.prefill_chunk or n
        total = 0
        while n > 0:
            piece = min(n, chunk)
            total += self._bucket_for(piece)
            n -= piece
        return total

    def _advance_chunk(self, st):
        """Dispatch the next prefill piece for an admitted request:
        the whole remaining suffix when chunking is off (or it fits),
        else one ``prefill_chunk``-sized piece. The FINAL piece
        samples the first token in-program and (prefix cache on)
        retains the freshly built prompt K/V in the pool. Returns True
        once the final piece is dispatched."""
        req, slot = st["req"], st["slot"]
        params, aux = self._dec._params, self._dec._aux
        start = st["next"]
        p = len(req.prompt)
        remaining = p - start
        piece = remaining if self.prefill_chunk == 0 \
            else min(remaining, self.prefill_chunk)
        final = start + piece == p
        if start == 0 and piece == p and st["dev"] is not None:
            dev = st["dev"]            # staged whole-prompt h2d
            bucket = int(dev.shape[1])
        else:
            bucket = self._bucket_for(piece)
            chunk = np.zeros((1, bucket), np.int32)
            chunk[0, :piece] = req.prompt[start:start + piece]
            dev = chunk
        fn = self._prefill_fn(bucket)
        with tele.span("serving.prefill", cat="serving", bucket=bucket,
                       slot=slot, start=start):
            self._caches, self._state, t0 = fn(
                params, aux, self._caches, self._state,
                np.int32(slot), dev, np.int32(start), np.int32(piece),
                np.bool_(final), np.float32(req.temperature),
                _raw_key(req.seed),
                np.int32(-1 if req.eos_id is None else req.eos_id),
                np.int32(req.limit))
        req.prefill_chunks += 1
        st["next"] = start + piece
        self.stats["prefill_chunks"] += 1
        self._round_budget -= piece
        if not final:
            return False
        self._drain.append(("prefill", req, slot, t0))
        self.stats["prefills"] += 1
        _TM_PREFILLS.inc()
        _TM_CHUNKS.observe(req.prefill_chunks)
        if st["entry"] is not None:
            self._prefix.release(st["entry"])
        # a duplicate prompt admitted while this one was mid-chunk may
        # have finished first and retained the same tokens — its rows
        # are already byte-identical, so re-copying is a wasted dispatch
        if st["insert"] and self._prefix.get(req.prompt) is None:
            ev0 = self._prefix.evictions
            new = self._prefix.insert(req.prompt)
            _TM_PREFIX_EVICTIONS.inc(self._prefix.evictions - ev0)
            if new is None:
                _TM_PREFIX_INSERT_SKIPPED.inc()
            else:
                # the slot's rows [0, P) ARE the prompt K/V right now —
                # the retention copy is ordered before the slot's
                # decode writes by the cache-tree data dependency
                self._dispatch_copy(p, src=slot, dst=new.slot,
                                    src_pool=False, dst_pool=True)
            _TM_PREFIX_BYTES.set(self._prefix.bytes_used)
        return True

    def _busy(self):
        return (self.slots - len(self._free)) > 0 or bool(self._pending) \
            or self._stager.staged() > 0 or self._held is not None

    def _push_token(self, req, slot, t, done_now):
        assert t >= 0, "drained a token from a device-dead slot"
        now = time.perf_counter()
        req.tokens.append(int(t))
        if req.t_first is None:
            req.t_first = now
            _TM_TTFT_MS.observe((now - req.t_submit) * 1e3)
        self.stats["tokens"] += 1
        _TM_TOKENS.inc()
        hit_eos = req.eos_id is not None and t == req.eos_id
        if hit_eos or len(req.tokens) >= req.limit:
            req.done = True
            req.t_done = now
            req.retire_reason = "eos" if hit_eos else "length"
            (_TM_RETIRED_EOS if hit_eos else _TM_RETIRED_LENGTH).inc()
            _TM_COMPLETED.inc()
            if len(req.tokens) > 1:
                _TM_CADENCE_MS.observe(
                    (req.t_done - req.t_first)
                    / (len(req.tokens) - 1) * 1e3)
            self._mirror[slot] = None
            self._free.append(slot)
            self.stats["completed"] += 1
            done_now.append(req)

    def _drain_one(self, done_now):
        entry = self._drain.popleft()
        if entry[0] == "prefill":
            _, req, slot, t0 = entry
            self._mirror[slot] = req
            self._push_token(req, slot, int(np.asarray(t0)), done_now)
        else:
            rounds = np.asarray(entry[1])        # [steps_per_round, S]
            for row in rounds:
                for s in range(self.slots):
                    req = self._mirror[s]
                    if req is not None:
                        self._push_token(req, s, int(row[s]), done_now)

    def step(self):
        """One scheduling round: advance every mid-prefill request by
        ONE chunk, admit staged requests into free slots (prefix copy
        + first prefill piece), dispatch ONE decode round
        (``steps_per_round`` fused all-slot steps) if any decodable
        slot is occupied, then drain output vectors that are
        ``drain_depth`` dispatches old (all of them once nothing is in
        flight). Returns the requests COMPLETED by this round, in
        completion order."""
        done_now = []
        # chunked prefill, Sarathi-style per-round budget: at most
        # ~prefill_chunk tokens of prefill work run between decode
        # rounds — ONE piece of the oldest parked request, then
        # admissions' first pieces until the budget is spent (_admit
        # holds the overflow request for next round). Resident
        # decoders therefore stall at most one budget's worth of
        # prefill per round, however many long prompts are in flight.
        self._round_budget = self.prefill_chunk or float("inf")
        if self._chunking:
            st = self._chunking.popleft()
            if not self._advance_chunk(st):
                self._chunking.append(st)
        admitted = self._admit()
        busy = self.slots - len(self._free)
        _TM_OCCUPANCY.set(busy)
        if admitted or busy:
            # zero-admission rounds COUNT while work is resident (they
            # are what admission starvation looks like — the histogram's
            # 0 bucket exists for them); only fully-idle polls are
            # not a scheduling round
            _TM_ADMITTED.observe(admitted)
        # slots still mid-prefill have nothing to decode: a round with
        # ONLY those resident would be pure wasted dispatch
        if busy - len(self._chunking) > 0:
            with tele.span("serving.decode_round", cat="serving",
                           slots_busy=busy):
                self._caches, self._state, out = self._step_fn(
                    self._dec._params, self._dec._aux,
                    self._caches, self._state)
            self._drain.append(("step", out))
            self.stats["steps"] += 1
            _TM_ROUNDS.inc()
            _TM_SLOTS_BUSY.observe(busy)
        while len(self._drain) > (self._drain_depth if self._busy()
                                  else 0):
            self._drain_one(done_now)
        return done_now

    def serve_forever(self, requests=None):
        """Drive the loop to completion: pull submissions from
        ``requests`` (optional iterable — dict kwargs for
        :meth:`submit`, a ``(prompt, kwargs)`` pair, a bare prompt
        array, or ``None`` meaning "nothing has arrived yet", which
        lets a generator pace an online arrival process), stepping
        continuously; between pulls the engine keeps serving whatever
        is resident. Returns all completed requests in completion
        order. With ``requests=None`` it serves what was already
        submitted and returns when idle."""
        completed = []
        src = iter(requests) if requests is not None else None
        exhausted = src is None
        while True:
            # ingest until backpressure or a pacing None — one item per
            # round would starve free slots while the source has ready
            # requests
            while not exhausted and self.queued() < self.max_queue:
                try:
                    item = next(src)
                except StopIteration:
                    exhausted = True
                    break
                if item is None:
                    break              # nothing ready yet: go decode
                if isinstance(item, dict):
                    self.submit(**item)
                elif isinstance(item, tuple) and len(item) == 2 \
                        and isinstance(item[1], dict):
                    self.submit(item[0], **item[1])
                else:
                    self.submit(item, max_tokens=self.max_len)
            completed.extend(self.step())
            if exhausted and self.idle:
                return completed
