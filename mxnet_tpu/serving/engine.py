"""Continuous-batching inference engine over a slot-paged KV cache.

Architecture (doc/serving.md has the full story):

* ONE persistent KV cache of ``S`` slots x ``max_len`` — ``Decoder``'s
  own cache layouts (plain float, int8-quantized scales, sliding-window
  rings) with the batch axis reinterpreted as a SLOT axis. A request
  occupies one slot from admission to retirement; a freed slot is
  recycled without touching the others (stale rows are hidden by the
  ``key_pos <= pos`` causal mask until overwritten; window rings get
  their position buffers reset at admission).

* TWO compiled program families serve any request mix, ever:

  - **bucketed prefill** (one program per power-of-2 length bucket):
    a prompt padded to its bucket is pushed through the derived
    incremental graph at positions ``[0, P)`` of its assigned slot —
    slot index, true length, temperature, rng key, eos id and token
    budget are all traced operands. The first output token is sampled
    in-program and the per-slot state vectors are scatter-updated, so
    admission costs zero extra compiled programs.
  - **fused decode step** (exactly one program): one token for EVERY
    slot at its own position — per-slot position vector, per-slot
    temperature/rng sampling, vectorized EOS/length masking. Finished
    slots freeze (their write is idempotent) until reused.

* a host-side scheduler that admits queued requests into freed slots
  BETWEEN device steps (iteration-level / continuous batching — Orca,
  OSDI '22), retires finished sequences, and overlaps host work with
  device execution twice over: prompt h2d staging rides the unified
  depth-k ``io.StagedStream`` helper (PR 2's machinery), and output
  token vectors are drained ``drain_depth`` dispatches behind the
  device, so the step stream never blocks on either edge.

Determinism guarantees (pinned by tests/test_serving.py): greedy
(``temperature=0``) outputs are byte-identical to offline
``Decoder.generate`` per request, regardless of admission order, slot
assignment, co-resident requests, or bucket padding; sampled outputs
depend only on ``(seed, position)`` — not on scheduling.
"""
from __future__ import annotations

import collections
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .. import telemetry as tele
from ..io import StagedStream
from ..parallel.decode import Decoder

__all__ = ["InferenceEngine", "Request"]

# per-request serving stats (doc/observability.md "serving"): all
# host-side perf_counter arithmetic on values the scheduler already
# tracks — nothing new crosses the device boundary
_TM_QUEUE_WAIT_MS = tele.histogram("serving.queue_wait_ms")
_TM_TTFT_MS = tele.histogram("serving.ttft_ms")
_TM_CADENCE_MS = tele.histogram("serving.token_cadence_ms")
_TM_TOKENS = tele.counter("serving.tokens")
_TM_COMPLETED = tele.counter("serving.completed")
_TM_RETIRED_EOS = tele.counter("serving.retired_eos")
_TM_RETIRED_LENGTH = tele.counter("serving.retired_length")
_TM_ROUNDS = tele.counter("serving.rounds")
_TM_PREFILLS = tele.counter("serving.prefills")
_TM_ADMITTED = tele.histogram(
    "serving.admitted_per_round", buckets=(0, 1, 2, 4, 8, 16, 32, 64))
_TM_SLOTS_BUSY = tele.histogram(
    "serving.slots_busy_per_round",
    buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128))
_TM_OCCUPANCY = tele.gauge("serving.slot_occupancy")
# compile_counts re-exported as telemetry: the in-engine log stays the
# tested contract; these make recompiles visible in ONE snapshot next
# to everything else
_TM_COMPILE_DECODE = tele.counter("serving.compiles_decode")
_TM_COMPILE_PREFILL = tele.counter("serving.compiles_prefill")


class Request:
    """One submitted generation request (handle returned by
    :meth:`InferenceEngine.submit`).

    ``tokens`` fills in as output drains: generated ids only (no
    prompt echo), including ``eos_id`` when hit. ``done`` flips when
    the sequence retires; ``result()`` returns the tokens as int32
    numpy. Latency probes: ``t_submit``/``t_admit``/``t_first``/
    ``t_done`` (perf_counter seconds; admit = slot assigned + prefill
    dispatched; first = first token DRAINED, i.e. visible to the
    caller, not merely computed). ``retire_reason`` is ``"eos"`` or
    ``"length"`` once done. The same breakdown feeds the
    ``serving.*`` telemetry histograms (queue wait / TTFT / per-token
    cadence — doc/observability.md).
    """

    def __init__(self, rid, prompt, max_tokens, eos_id, temperature,
                 seed, limit):
        self.id = rid
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.eos_id = eos_id
        self.temperature = temperature
        self.seed = seed
        self.limit = limit          # min(max_tokens, max_len - P)
        self.tokens = []
        self.done = False
        self.t_submit = time.perf_counter()
        self.t_admit = None
        self.t_first = None
        self.t_done = None
        self.retire_reason = None

    def result(self):
        if not self.done:
            raise MXNetError("request %s is not finished" % self.id)
        return np.asarray(self.tokens, np.int32)

    def __repr__(self):
        return ("Request(id=%r, prompt_len=%d, max_tokens=%d, done=%s, "
                "generated=%d)" % (self.id, len(self.prompt),
                                   self.max_tokens, self.done,
                                   len(self.tokens)))


class _PendingSource:
    """StagedStream source over the engine's pending deque (empty deque
    = StopIteration; the stream runs ``live_source`` mode, so submits
    arriving later are staged by the very next fill)."""

    def __init__(self, dq):
        self._dq = dq

    def next(self):
        if not self._dq:
            raise StopIteration
        return self._dq.popleft()

    def reset(self):
        pass


def _default_buckets(max_len):
    out, b = [], 16
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


def _raw_key(seed):
    """threefry PRNGKey layout without dispatching a device op (the
    compile-count contract stays clean): [hi32, lo32] of the seed."""
    seed = int(seed) & 0xFFFFFFFFFFFFFFFF
    return np.array([seed >> 32, seed & 0xFFFFFFFF], np.uint32)


class InferenceEngine:
    """Continuous-batching serving loop over a :class:`Decoder`.

    Parameters
    ----------
    decoder : Decoder
        The derived incremental program (any cache flavor: bf16/int8
        ``cache_dtype``, sliding-window models, GQA, rope). Build one
        with ``Decoder(symbol, params, max_len=...)`` or use
        :meth:`from_checkpoint` / ``FeedForward.as_serving_engine``.
        ``cache_block`` prefix-bounded reads are not supported under
        slot addressing (each slot has its own clock) — construct the
        decoder with ``cache_block=None`` (the engine refuses
        otherwise rather than silently decoding differently).
    slots : int
        ``S``, the resident-sequence capacity — the continuous batch
        size and the cache's slot-axis length. Throughput knob: decode
        cost per step is roughly flat until the chip saturates, so
        more slots = more tokens per step (tools/bench_serving.py
        sweeps it).
    prefill_buckets : tuple of int, optional
        Ascending prompt-padding lengths; a prompt takes the smallest
        bucket >= its length (default: powers of two from 16, capped
        at ``max_len``). One prefill program compiles per bucket
        actually used — the whole compile budget is
        ``len(buckets) + 1``.
    max_queue : int
        Backpressure bound on submitted-but-not-admitted requests;
        ``submit`` raises ``MXNetError`` beyond it.
    stage_depth : int
        Depth of the prompt h2d stager (``io.StagedStream``).
    drain_depth : int
        How many step outputs may remain un-drained while work is in
        flight — the d2h analogue of ``stage_depth``. Retirement is
        discovered at drain time, so a slot frees at most
        ``drain_depth`` rounds after its sequence finished (the device
        freezes finished slots in the meantime).
    steps_per_round : int
        Tokens decoded per dispatched round: the decode program is a
        ``lax.scan`` of this many fused all-slots steps, amortizing
        the per-dispatch host/relay overhead k-fold (one jit call,
        one [k, S] output drain per k tokens). Admission/retirement
        granularity coarsens to k tokens — a slot freed mid-round sits
        frozen until the round ends, so k should stay well under the
        typical output length (k=1 is latency-optimal per-token
        scheduling; the chip-facing bench uses 8). Still ONE compiled
        decode program either way.
    """

    def __init__(self, decoder, slots=8, prefill_buckets=None,
                 max_queue=256, stage_depth=2, drain_depth=2,
                 steps_per_round=1):
        if not isinstance(decoder, Decoder):
            raise MXNetError("InferenceEngine needs a Decoder, got %r"
                             % type(decoder).__name__)
        if decoder._cache_block is not None:
            raise MXNetError(
                "InferenceEngine: slot-paged decoding does not support "
                "cache_block prefix-bounded reads (per-slot positions); "
                "build the Decoder with cache_block=None")
        self._dec = decoder
        self.max_len = decoder.max_len
        self.slots = int(slots)
        if self.slots < 1:
            raise MXNetError("InferenceEngine: slots must be >= 1")
        if prefill_buckets is None:
            prefill_buckets = _default_buckets(self.max_len)
        buckets = tuple(int(b) for b in prefill_buckets)
        if not buckets or list(buckets) != sorted(set(buckets)) \
                or buckets[0] < 1 or buckets[-1] > self.max_len:
            raise MXNetError(
                "InferenceEngine: prefill_buckets must be strictly "
                "ascending lengths in [1, max_len], got %r" % (buckets,))
        self.prefill_buckets = buckets
        self.max_queue = int(max_queue)
        self._drain_depth = max(0, int(drain_depth))
        self.steps_per_round = int(steps_per_round)
        if self.steps_per_round < 1:
            raise MXNetError("InferenceEngine: steps_per_round must "
                             "be >= 1")

        # device-resident: the slot-paged cache + per-slot state vectors
        S = self.slots
        self._caches = decoder.init_cache(S)
        self._state = (
            jnp.zeros((S,), jnp.int32),        # pos: next write position
            jnp.zeros((S,), jnp.int32),        # tok: last sampled token
            jnp.zeros((S,), bool),             # live
            jnp.zeros((S,), jnp.float32),      # temperature
            jnp.zeros((S, 2), jnp.uint32),     # rng key
            jnp.full((S,), -1, jnp.int32),     # eos id (-1: none)
            jnp.zeros((S,), jnp.int32),        # last allowed position
        )

        # host-side scheduler state
        self._pending = collections.deque()
        self._stager = StagedStream(_PendingSource(self._pending),
                                    place=self._place_prompt,
                                    depth=stage_depth, live_source=True)
        self._free = collections.deque(range(S))  # FIFO slot recycling
        self._mirror = [None] * S   # drain-side view: slot -> Request
        self._drain = collections.deque()
        self._next_id = 0
        self._auto_seed = 0
        self.stats = {"submitted": 0, "completed": 0, "prefills": 0,
                      "steps": 0, "tokens": 0}

        # the two compiled program families; the log records one tag
        # per TRACE (python side effects run at trace time only), so it
        # IS the compile count — tests pin the contract against it
        self._compile_log = []
        self._donate = (2, 3) if jax.default_backend() != "cpu" else ()
        self._step_fn = jax.jit(self._make_step(),
                                donate_argnums=self._donate)
        self._prefill_fns = {}

    # -- construction ---------------------------------------------------
    @classmethod
    def from_checkpoint(cls, prefix, epoch, max_len, slots=8,
                        prefill_buckets=None, max_queue=256,
                        stage_depth=2, drain_depth=2, steps_per_round=1,
                        **decoder_kwargs):
        """Checkpoint → serving engine in one call
        (``prefix-symbol.json`` + ``prefix-NNNN.params``, the reference
        format): builds the :class:`Decoder` via
        ``Decoder.from_checkpoint`` and wraps it. ``decoder_kwargs``
        reach the decoder (``compute_dtype``, ``cache_dtype``, ...)."""
        decoder_kwargs.setdefault("cache_block", None)
        dec = Decoder.from_checkpoint(prefix, epoch, max_len,
                                      **decoder_kwargs)
        return cls(dec, slots=slots, prefill_buckets=prefill_buckets,
                   max_queue=max_queue, stage_depth=stage_depth,
                   drain_depth=drain_depth,
                   steps_per_round=steps_per_round)

    # -- compiled programs ----------------------------------------------
    def _make_step(self):
        dec = self._dec
        k_rounds = self.steps_per_round

        def one_step(caches, state, params, aux):
            pos, tok, live, temp, keys, eos, last = state
            # write each slot's pending token at ITS position, read
            # logits for the next one (frozen slots rewrite their last
            # token in place — idempotent)
            logits, caches = dec._run_slots(params, aux, caches, pos,
                                            tok[:, None])
            logits = logits[:, 0]
            nxt_pos = pos + 1
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

            def with_sampling(_):
                t = jnp.where(temp > 0.0, temp, jnp.float32(1.0))

                def draw(k, q, row):
                    return jax.random.categorical(
                        jax.random.fold_in(k, q), row)

                sampled = jax.vmap(draw)(
                    keys, nxt_pos,
                    logits.astype(jnp.float32) / t[:, None]
                ).astype(jnp.int32)
                return jnp.where(temp > 0.0, sampled, greedy)

            # all-greedy rounds (the common case) must not pay the
            # per-slot fold_in + categorical they will never take —
            # same reasoning as Decoder._build_generate's lax.cond
            nxt = lax.cond(jnp.any(temp > 0.0), with_sampling,
                           lambda _: greedy, None)
            done_now = (nxt == eos) | (nxt_pos >= last)
            out = jnp.where(live, nxt, -1)     # -1: slot had no token
            live2 = live & ~done_now
            pos2 = jnp.where(live, nxt_pos, pos)
            tok2 = jnp.where(live, nxt, tok)
            return caches, (pos2, tok2, live2, temp, keys, eos, last), \
                out

        def step(params, aux, caches, state):
            self._compile_log.append("decode")  # trace-time, see above
            _TM_COMPILE_DECODE.inc()

            def body(carry, _):
                caches, st = carry
                caches, st, out = one_step(caches, st, params, aux)
                return (caches, st), out

            (caches, state), outs = lax.scan(body, (caches, state),
                                             None, length=k_rounds)
            return caches, state, outs          # outs [k, S]

        return step

    def _prefill_fn(self, bucket):
        if bucket not in self._prefill_fns:
            dec = self._dec

            def prefill(params, aux, caches, state, slot, tokens,
                        true_len, temp, key, eos, max_toks):
                self._compile_log.append(("prefill", bucket))
                _TM_COMPILE_PREFILL.inc()
                pos, tok, live, temps, keys, eoss, lasts = state
                sub = dec.slot_slice(caches, slot)
                # ring-position reset: a recycled slot must not leak
                # the previous occupant's window entries
                sub = dec.clear_window_positions(sub)
                # valid_len: pad rows must not enter window rings
                # (they would EVICT real in-window keys — linear cache
                # rows are masked-until-overwritten, ring slots wrap)
                logits, sub = dec._run(params, aux, sub, 0, tokens,
                                       valid_len=true_len)
                caches = dec.slot_update(caches, slot, sub)
                v = logits.shape[2]
                zero = jnp.int32(0)
                lastlog = lax.dynamic_slice(
                    logits, (zero, true_len - 1, zero), (1, 1, v))[0, 0]
                greedy = jnp.argmax(lastlog, -1).astype(jnp.int32)
                t = jnp.where(temp > 0.0, temp, jnp.float32(1.0))
                sampled = jax.random.categorical(
                    jax.random.fold_in(key, true_len),
                    lastlog.astype(jnp.float32) / t).astype(jnp.int32)
                t0 = jnp.where(temp > 0.0, sampled, greedy)
                lastp = jnp.minimum(true_len + max_toks - 1,
                                    dec.max_len - 1).astype(jnp.int32)
                done0 = (t0 == eos) | (true_len >= lastp)
                state2 = (pos.at[slot].set(true_len),
                          tok.at[slot].set(t0),
                          live.at[slot].set(~done0),
                          temps.at[slot].set(temp),
                          keys.at[slot].set(key),
                          eoss.at[slot].set(eos),
                          lasts.at[slot].set(lastp))
                return caches, state2, t0

            self._prefill_fns[bucket] = jax.jit(
                prefill, donate_argnums=self._donate)
        return self._prefill_fns[bucket]

    @property
    def compile_counts(self):
        """{'decode': n_traces, 'prefill': {bucket: n_traces}} — the
        compile-count contract: after any workload, decode == 1 and
        each USED bucket == 1 (doc/serving.md)."""
        out = {"decode": 0, "prefill": {}}
        for tag in self._compile_log:
            if tag == "decode":
                out["decode"] += 1
            else:
                out["prefill"][tag[1]] = out["prefill"].get(tag[1], 0) + 1
        return out

    # -- host scheduler -------------------------------------------------
    def _bucket_for(self, n):
        for b in self.prefill_buckets:
            if b >= n:
                return b
        raise MXNetError(
            "InferenceEngine: prompt length %d exceeds the largest "
            "prefill bucket %d" % (n, self.prefill_buckets[-1]))

    def _place_prompt(self, req):
        """Stager place fn: pad to the bucket and dispatch the h2d
        (async) — runs up to stage_depth requests ahead of admission."""
        p = len(req.prompt)
        bucket = self._bucket_for(p)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :p] = req.prompt
        return req, jax.device_put(padded)

    def queued(self):
        """Requests submitted but not yet admitted to a slot."""
        return len(self._pending) + self._stager.staged()

    @property
    def idle(self):
        return not self._pending and self._stager.staged() == 0 \
            and len(self._free) == self.slots and not self._drain

    def submit(self, prompt, max_tokens, eos_id=None, temperature=0.0,
               seed=None, request_id=None):
        """Queue one generation request; returns its :class:`Request`
        handle (fills in as the engine steps).

        prompt : 1-D int sequence, ``1 <= len <= max_len - 1`` (and
        within the largest bucket). ``max_tokens`` is truncated to the
        cache: at most ``max_len - len(prompt)`` tokens come back.
        ``eos_id``: generation stops after emitting it (included in
        the output). ``temperature=0``: greedy, byte-identical to
        ``Decoder.generate``; > 0 samples with ``seed`` (auto-drawn if
        omitted) — reproducible and schedule-independent.

        Raises ``MXNetError`` once ``max_queue`` requests are waiting
        (backpressure — callers drive :meth:`step` to drain).
        """
        if self.queued() >= self.max_queue:
            raise MXNetError(
                "InferenceEngine: request queue is full (%d waiting; "
                "max_queue=%d) — step() the engine to drain it"
                % (self.queued(), self.max_queue))
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise MXNetError("InferenceEngine: empty prompt")
        if prompt.size > self.max_len - 1:
            raise MXNetError(
                "InferenceEngine: prompt length %d leaves no room to "
                "generate (max_len=%d)" % (prompt.size, self.max_len))
        self._bucket_for(prompt.size)  # validate against buckets now
        max_tokens = int(max_tokens)
        if max_tokens < 1:
            raise MXNetError("InferenceEngine: max_tokens must be >= 1")
        if seed is None:
            seed = self._auto_seed
            self._auto_seed += 1
        rid = request_id
        if rid is None:
            rid = self._next_id
            self._next_id += 1
        limit = min(max_tokens, self.max_len - prompt.size)
        req = Request(rid, prompt, max_tokens, eos_id,
                      float(temperature), seed, limit)
        self._pending.append(req)
        self.stats["submitted"] += 1
        return req

    def _admit(self):
        """Fill freed slots from the staged queue: one prefill dispatch
        per admission, between device steps (iteration-level
        scheduling). Returns how many requests were admitted."""
        params, aux = self._dec._params, self._dec._aux
        admitted = 0
        while self._free:
            try:
                req, dev = self._stager.next()
            except StopIteration:
                break
            slot = self._free.popleft()
            bucket = int(dev.shape[1])
            fn = self._prefill_fn(bucket)
            req.t_admit = time.perf_counter()
            _TM_QUEUE_WAIT_MS.observe(
                (req.t_admit - req.t_submit) * 1e3)
            with tele.span("serving.prefill", cat="serving",
                           bucket=bucket, slot=slot):
                self._caches, self._state, t0 = fn(
                    params, aux, self._caches, self._state,
                    np.int32(slot), dev, np.int32(len(req.prompt)),
                    np.float32(req.temperature), _raw_key(req.seed),
                    np.int32(-1 if req.eos_id is None else req.eos_id),
                    np.int32(req.limit))
            self._drain.append(("prefill", req, slot, t0))
            self.stats["prefills"] += 1
            _TM_PREFILLS.inc()
            admitted += 1
        return admitted

    def _busy(self):
        return (self.slots - len(self._free)) > 0 or bool(self._pending) \
            or self._stager.staged() > 0

    def _push_token(self, req, slot, t, done_now):
        assert t >= 0, "drained a token from a device-dead slot"
        now = time.perf_counter()
        req.tokens.append(int(t))
        if req.t_first is None:
            req.t_first = now
            _TM_TTFT_MS.observe((now - req.t_submit) * 1e3)
        self.stats["tokens"] += 1
        _TM_TOKENS.inc()
        hit_eos = req.eos_id is not None and t == req.eos_id
        if hit_eos or len(req.tokens) >= req.limit:
            req.done = True
            req.t_done = now
            req.retire_reason = "eos" if hit_eos else "length"
            (_TM_RETIRED_EOS if hit_eos else _TM_RETIRED_LENGTH).inc()
            _TM_COMPLETED.inc()
            if len(req.tokens) > 1:
                _TM_CADENCE_MS.observe(
                    (req.t_done - req.t_first)
                    / (len(req.tokens) - 1) * 1e3)
            self._mirror[slot] = None
            self._free.append(slot)
            self.stats["completed"] += 1
            done_now.append(req)

    def _drain_one(self, done_now):
        entry = self._drain.popleft()
        if entry[0] == "prefill":
            _, req, slot, t0 = entry
            self._mirror[slot] = req
            self._push_token(req, slot, int(np.asarray(t0)), done_now)
        else:
            rounds = np.asarray(entry[1])        # [steps_per_round, S]
            for row in rounds:
                for s in range(self.slots):
                    req = self._mirror[s]
                    if req is not None:
                        self._push_token(req, s, int(row[s]), done_now)

    def step(self):
        """One scheduling round: admit staged requests into free slots,
        dispatch ONE decode round (``steps_per_round`` fused all-slot
        steps) if any slot is occupied, then drain output vectors that
        are ``drain_depth`` dispatches old (all of them once nothing
        is in flight). Returns the requests COMPLETED by this round,
        in completion order."""
        done_now = []
        admitted = self._admit()
        busy = self.slots - len(self._free)
        _TM_OCCUPANCY.set(busy)
        if admitted or busy:
            # zero-admission rounds COUNT while work is resident (they
            # are what admission starvation looks like — the histogram's
            # 0 bucket exists for them); only fully-idle polls are
            # not a scheduling round
            _TM_ADMITTED.observe(admitted)
        if busy > 0:
            with tele.span("serving.decode_round", cat="serving",
                           slots_busy=busy):
                self._caches, self._state, out = self._step_fn(
                    self._dec._params, self._dec._aux,
                    self._caches, self._state)
            self._drain.append(("step", out))
            self.stats["steps"] += 1
            _TM_ROUNDS.inc()
            _TM_SLOTS_BUSY.observe(busy)
        while len(self._drain) > (self._drain_depth if self._busy()
                                  else 0):
            self._drain_one(done_now)
        return done_now

    def serve_forever(self, requests=None):
        """Drive the loop to completion: pull submissions from
        ``requests`` (optional iterable — dict kwargs for
        :meth:`submit`, a ``(prompt, kwargs)`` pair, a bare prompt
        array, or ``None`` meaning "nothing has arrived yet", which
        lets a generator pace an online arrival process), stepping
        continuously; between pulls the engine keeps serving whatever
        is resident. Returns all completed requests in completion
        order. With ``requests=None`` it serves what was already
        submitted and returns when idle."""
        completed = []
        src = iter(requests) if requests is not None else None
        exhausted = src is None
        while True:
            # ingest until backpressure or a pacing None — one item per
            # round would starve free slots while the source has ready
            # requests
            while not exhausted and self.queued() < self.max_queue:
                try:
                    item = next(src)
                except StopIteration:
                    exhausted = True
                    break
                if item is None:
                    break              # nothing ready yet: go decode
                if isinstance(item, dict):
                    self.submit(**item)
                elif isinstance(item, tuple) and len(item) == 2 \
                        and isinstance(item[1], dict):
                    self.submit(item[0], **item[1])
                else:
                    self.submit(item, max_tokens=self.max_len)
            completed.extend(self.step())
            if exhausted and self.idle:
                return completed
