"""Online serving: continuous-batching inference over a slot-paged
KV cache (doc/serving.md).

The offline :class:`~mxnet_tpu.parallel.Decoder` compiles one program
per exact ``(batch, prompt_len, num_steps)`` shape and stalls a whole
batch on its slowest sequence; the :class:`InferenceEngine` here serves
an arbitrary request mix — mixed prompt lengths, per-request
``max_tokens``/``eos_id``/temperature, requests arriving mid-stream —
from exactly two compiled program families (a bucketed prefill and a
fused all-slots decode step) with iteration-level scheduling between
device steps (Orca, OSDI '22; slot-structured caches after vLLM's
PagedAttention, SOSP '23).
"""
from .engine import InferenceEngine, Request

__all__ = ["InferenceEngine", "Request"]
