"""Online serving: continuous-batching inference over a slot-paged
KV cache (doc/serving.md).

The offline :class:`~mxnet_tpu.parallel.Decoder` compiles one program
per exact ``(batch, prompt_len, num_steps)`` shape and stalls a whole
batch on its slowest sequence; the :class:`InferenceEngine` here serves
an arbitrary request mix — mixed prompt lengths, per-request
``max_tokens``/``eos_id``/temperature, requests arriving mid-stream —
from a few compiled program families (a bucketed prefill that also
serves chunked prefill, a fused all-slots decode step, a bucketed
prefix-cache row copy, and — with speculation on — ONE draft-and-
verify step emitting up to ``spec_k + 1`` tokens per weights read)
with iteration-level scheduling between device steps (Orca, OSDI '22;
slot-structured caches after vLLM's PagedAttention, SOSP '23; prefix
reuse after RadixAttention, chunk-interleaved prefill after
Sarathi-Serve, and draft-and-verify decoding after Leviathan et al.
2023 with prompt-lookup/n-gram drafting per the PLD/lookahead line —
:class:`NgramDrafter`).

Robustness layer (doc/serving.md "Serving under hostile traffic"):
per-request deadlines and :meth:`InferenceEngine.cancel`, overload
shedding (:class:`EngineOverloaded`), a round watchdog
(:class:`EngineStuck`), poisoned-request isolation, crash-safe
:meth:`InferenceEngine.snapshot` / :meth:`InferenceEngine.restore`,
and a :meth:`InferenceEngine.close` shutdown path
(:class:`EngineClosed`) — all host-side, the compiled program
families above are frozen.

Fleet layer (doc/fault_tolerance.md "Fleet resilience"):
:class:`FleetRouter` fronts N replicas with health-driven +
prefix-affinity admission, heartbeat failover, live request migration
(``drain``), and fleet-wide overload composition — a rolling restart
fails zero requests, byte-identically. Replicas may specialize
(``role="prefill"``/``"decode"``, doc/serving.md "Disaggregated
prefill/decode"): prefill engines hand finished KV rows to decode
engines through the router (:class:`KVHandoff`), isolating decode
cadence from long-prompt prefill. Every fleet request carries a trace
context across those hops; the router's
:class:`FleetFlightRecorder` stitches router + wire + per-engine
events into one cross-replica timeline with an end-to-end SLO
decomposition (doc/observability.md "The fleet tracing plane").
"""
from .capture import CaptureStream, load_capture
from .engine import (InferenceEngine, Request, EngineOverloaded,
                     EngineClosed, EngineStuck)
from .fleet import FleetRouter, FleetRequest, FleetFlightRecorder
from .flight import FlightRecorder
from .handoff import KVHandoff, pack_rows, unpack_rows
from .prefix import PrefixCache
from .quant import (QuantizedTensor, quantize_tensor, quantize_params,
                    quantized_weight_names, dequantize)
from .spec import NgramDrafter

__all__ = ["InferenceEngine", "Request", "PrefixCache",
           "FlightRecorder", "NgramDrafter", "CaptureStream",
           "load_capture", "QuantizedTensor", "quantize_tensor",
           "quantize_params", "quantized_weight_names", "dequantize",
           "EngineOverloaded", "EngineClosed", "EngineStuck",
           "FleetRouter", "FleetRequest", "FleetFlightRecorder",
           "KVHandoff", "pack_rows", "unpack_rows"]
