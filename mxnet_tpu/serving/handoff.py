"""KV handoff between role-specialized serving engines.

Disaggregated prefill/decode serving (the Splitwise/DistServe shape)
splits the two phases of a request across SPECIALIST engines: a
prefill-role engine runs admission + bucketed/chunked prefill only and
then hands the finished request — its live KV rows, sampling identity,
and the first emitted token — to a decode-role engine, which continues
it byte-identically to what one unified engine would have produced.
Long-prompt prefill rounds then never share a dispatch queue with
anyone's decode cadence, which is the whole point: decode p99
isolation under a long-prompt adversarial mix.

This module is the WIRE FORMAT half of that split, deliberately free
of any scheduler knowledge:

* :class:`KVHandoff` — one packaged finished-prefill. It pins the
  source engine's slot until the router confirms delivery (or gives up
  and falls back to unified serving), exports the slot's KV rows
  lazily exactly once (retries re-serialize the cached export rather
  than touching the source cache again), and carries everything the
  decode side needs to resume: prompt, emitted tokens (including the
  prefill's first sampled token), sampling identity (temperature +
  resolved seed), eos/limit bounds, and the prefill length ``P`` whose
  rows the payload covers.
* :func:`pack_rows` / :func:`unpack_rows` — the transfer encoding.
  ``native`` ships rows at cache dtype; ``int8`` quantizes float rows
  per-row symmetric (amax/127 scales, the PR 15 tolerance contract) at
  about half the fp bytes. Integer cache leaves (an int8 KV cache) are
  already compact and always pass through, so int8 KV serialises at
  half the fp bytes with NO opt-in needed.

The scheduler half (role gating, export/import programs, exactly-once
admission) lives in :mod:`.engine`; placement, transport discipline,
and failure fallback live in :mod:`.fleet`.
"""
from __future__ import annotations

import time

import numpy as np

import jax

from ..base import MXNetError

__all__ = ["KVHandoff", "pack_rows", "unpack_rows", "HANDOFF_DTYPES"]

HANDOFF_DTYPES = ("native", "int8")


class _Quant:
    """One int8-quantized cache leaf: ``q`` (int8 rows) plus per-row
    f32 ``scale``. A plain class — NOT a pytree node — so tree_map
    over a packed payload treats it as a leaf."""

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    @property
    def nbytes(self):
        return self.q.nbytes + self.scale.nbytes

    def __repr__(self):
        return "_Quant(shape=%s, nbytes=%d)" % (self.q.shape, self.nbytes)


def _quantize(rows):
    """Per-row symmetric int8: scale over every axis but the row axis
    (axis 0 of an exported ``[rows, ...]`` leaf), amax/127 with a zero
    guard, round-and-clip. Matches the PR 15 weight-quant contract."""
    x = np.asarray(rows, np.float32)
    axes = tuple(range(1, x.ndim))
    scale = np.max(np.abs(x), axis=axes, keepdims=True) / 127.0
    scale = np.where(scale == 0.0, np.float32(1.0), scale).astype(np.float32)
    q = np.clip(np.rint(x / scale), -127, 127).astype(np.int8)
    return _Quant(q, scale)


def pack_rows(rows, dtype):
    """Encode an exported host-side cache-row tree for transfer.

    ``native`` passes every leaf through as-is; ``int8`` replaces each
    FLOAT leaf (f32/bf16 caches) with a :class:`_Quant` and leaves
    integer leaves (already-int8 KV) untouched. Returns
    ``(payload_tree, nbytes)`` where nbytes is what actually ships.
    """
    if dtype not in HANDOFF_DTYPES:
        raise MXNetError("pack_rows: unknown handoff dtype %r (one of %s)"
                         % (dtype, ", ".join(HANDOFF_DTYPES)))

    def enc_leaf(x):
        host = np.asarray(x)
        if dtype == "int8" and jax.numpy.issubdtype(x.dtype,
                                                    jax.numpy.floating):
            return _quantize(host)
        return host

    payload = jax.tree_util.tree_map(enc_leaf, rows)
    nbytes = sum(leaf.nbytes
                 for leaf in jax.tree_util.tree_leaves(payload))
    return payload, int(nbytes)


def unpack_rows(payload, template):
    """Decode a packed payload back to cache-dtype rows. ``template``
    is any tree with the SAME treedef as the payload whose leaf dtypes
    are the destination cache dtypes (the importing engine passes its
    live cache tree). Dequantized rows land at the template dtype, so
    an fp cache that opted into int8 transfer absorbs the quantization
    error here — once, before the write — and an int8 cache's integer
    leaves come back bit-exact."""
    def dec(x, ref):
        if isinstance(x, _Quant):
            return (x.q.astype(np.float32) * x.scale).astype(ref.dtype)
        return np.asarray(x)

    return jax.tree_util.tree_map(
        dec, payload, template,
        is_leaf=lambda x: isinstance(x, _Quant))


class KVHandoff:
    """One finished prefill packaged for delivery to a decode engine.

    Created by the source engine at the end of a prefill-role
    request's prefill round (``InferenceEngine._handoff_prefill``); the
    slot named here stays OUT of the source's free list until
    :meth:`resolve` runs — exactly once, on whichever terminal path
    the router drives the package down (delivered, deduped after a
    retry, or abandoned to unified fallback).
    """

    __slots__ = ("id", "prompt", "tokens", "max_tokens", "eos_id",
                 "temperature", "seed", "prefill_len", "last",
                 "prefill_seq", "slot", "source", "resolved",
                 "t_ready", "trace", "_packed", "_nbytes")

    def __init__(self, engine, req, slot):
        self.id = req.id
        self.prompt = np.asarray(req.prompt, np.int32)
        # tokens includes the first emitted token t0 (and any tokens a
        # prior resume carried in) — the decode side resumes AFTER it.
        self.tokens = [int(t) for t in req.tokens]
        self.max_tokens = int(req.max_tokens)
        self.eos_id = req.eos_id
        self.temperature = float(req.temperature)
        self.seed = int(req.seed)
        # P: positions covered by the exported rows == len(req.seq)
        # (prompt + previously-resumed tokens; t0 is sampled FROM the
        # last prefill logits and has no KV row yet).
        self.prefill_len = int(req.seq.size)
        # absolute last position, same clamp as _prefill_fn's lastp
        self.last = min(self.prefill_len + (req.limit - req.resumed) - 1,
                        engine.max_len - 1)
        self.prefill_seq = np.asarray(
            np.concatenate([self.prompt,
                            np.asarray(self.tokens[:-1], np.int32)])
            if len(self.tokens) > 1 else self.prompt, np.int32)
        self.slot = int(slot)
        self.source = engine
        self.resolved = False
        # fleet trace context ((trace_id, hop) or None) rides the
        # package so the decode side's flight events keep the fleet
        # identity across the wire.
        self.trace = getattr(req, "trace", None)
        self.t_ready = time.perf_counter()
        self._packed = None
        self._nbytes = 0

    def materialize(self):
        """Export + pack the KV rows, once; cached so a retried or
        re-routed delivery never re-reads the source cache."""
        if self._packed is None:
            rows = self.source._export_rows(self.slot, self.prefill_len)
            self._packed, self._nbytes = pack_rows(
                rows, self.source.handoff_dtype)
        return self._packed

    @property
    def nbytes(self):
        self.materialize()
        return self._nbytes

    def payload(self, with_rows=True):
        """The admission dict ``InferenceEngine.admit_handoff`` takes.
        ``with_rows=False`` ships identity only — the router uses it
        when the target's prefix pool already retains the full
        prefill, so the transfer is skipped entirely."""
        return {
            "id": self.id,
            "prompt": self.prompt,
            "tokens": list(self.tokens),
            "max_tokens": self.max_tokens,
            "eos_id": self.eos_id,
            "temperature": self.temperature,
            "seed": self.seed,
            "prefill_len": self.prefill_len,
            "last": self.last,
            "trace": self.trace,
            "rows": self.materialize() if with_rows else None,
        }

    def resolve(self):
        """Release the source-side slot (exactly once)."""
        self.source._resolve_handoff(self)

    def __repr__(self):
        return ("KVHandoff(id=%r, P=%d, slot=%d, resolved=%s)"
                % (self.id, self.prefill_len, self.slot, self.resolved))
