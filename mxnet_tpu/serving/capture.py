"""Traffic capture for the serving engine: the record half of the
serving time machine (doc/observability.md "The serving time
machine").

The flight recorder reconstructs what happened to one request; nothing
so far could reconstruct the TRAFFIC — once a request retires, the
stream of arrivals that produced a p99 blowup or a watchdog trip is
gone, and the incident cannot be rerun. The engine's defining property
makes that a waste: greedy outputs are byte-identical across admission
orders, speculation, chunking, prefix hits and snapshot/restore, so a
captured request stream can be replayed EXACTLY —
``tools/replay_serving.py`` turns any capture into an offline test
case (``--verify`` asserts the replayed tokens byte-match the captured
ones) and an A/B bench for any engine-config change.

:class:`CaptureStream` is a crash-safe, size-bounded JSONL appender:

* **one line per event**, flushed per record — a killed process leaves
  a readable log ending at the last completed line (the loader
  tolerates a torn final line from a crash mid-write);
* a **header** record first (capture format version + the engine
  geometry ``snapshot()`` reports), so replay can rebuild the same
  engine — or the same engine with overrides — without guessing;
* a **submit** record per accepted request: monotonic arrival time
  (seconds since capture start), request id, prompt token ids, the
  sampling identity (temperature, seed — draws are
  ``fold_in(seed, position)``, so they replay exactly), token budget,
  eos id, deadlines, and any resume prefix (a restored engine's
  resubmits capture as what they are);
* a **retire** record per captured request: the emitted tokens, the
  retire reason, and the TTFT / steady-cadence timings the replay
  reports its latency diff against.

Bounded: ``MXNET_SERVING_CAPTURE_MB`` (default 64) caps the file —
past the budget NEW submits stop being captured (counted in
``serving.capture_skipped``), but the retire record of an
already-captured submit always lands (flight-recorder terminal-event
precedent: a capture whose submits have no retires cannot be
``--verify``-replayed, and retires are bounded — at most one per
captured submit). Host-side only: recording is JSON serialization of
values the scheduler already has, under one lock, on the submit/retire
paths — never per token, never a device op.

Knobs: ``InferenceEngine(capture_dir=...)`` /
``MXNET_SERVING_CAPTURE_DIR`` (default unset = off) name the
directory; each engine opens its own ``mx_capture_<pid>_<n>.jsonl``
inside it. ``snapshot()`` carries ``capture_dir``, so a
``restore()``-ed engine keeps capturing into a fresh file in the same
directory — the crash cycle itself stays on tape.
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import threading

from .. import telemetry as tele
from ..base import MXNetError

_log = logging.getLogger(__name__)

__all__ = ["CaptureStream", "load_capture"]

CAPTURE_VERSION = 1

_TM_RECORDS = tele.counter("serving.capture_records")
_TM_SKIPPED = tele.counter("serving.capture_skipped")
_TM_BYTES = tele.gauge("serving.capture_bytes")

# per-process file counter: a restore() cycle (or several engines
# sharing one capture_dir) must never overwrite an earlier capture
_FILE_SEQ = itertools.count()


class CaptureStream:
    """Crash-safe JSONL traffic capture (one instance per
    :class:`~mxnet_tpu.serving.InferenceEngine`; build via
    :meth:`open`, which returns a disabled no-op stream when the knob
    is unset)."""

    def __init__(self, path, max_bytes, header):
        self.path = path
        self.max_bytes = int(max_bytes)
        self.skipped = 0
        self._captured = set()       # ids whose submit landed
        self._lock = threading.Lock()
        self._t0 = None              # set by the engine (perf_counter)
        self._f = None
        self.bytes_written = 0
        if path is None:
            return
        self._f = open(path, "w")
        self._write({"kind": "header", "version": CAPTURE_VERSION,
                     "engine": header}, always=True)

    @classmethod
    def open(cls, capture_dir, capture_mb, header, t0):
        """Open a capture in ``capture_dir`` (None/empty = the
        ``MXNET_SERVING_CAPTURE_DIR`` env default; still empty =
        capture off — a disabled stream whose methods are no-ops).
        ``capture_mb`` None = the ``MXNET_SERVING_CAPTURE_MB`` env
        default, else 64. ``t0`` is the engine's perf_counter origin
        for arrival timestamps."""
        if capture_dir is None:
            capture_dir = os.environ.get("MXNET_SERVING_CAPTURE_DIR") \
                or None
        if not capture_dir:
            st = cls(None, 0, None)     # capture off: all no-ops
            st._t0 = t0
            return st
        if capture_mb is None:
            capture_mb = float(os.environ.get(
                "MXNET_SERVING_CAPTURE_MB") or "64")
        if float(capture_mb) <= 0:
            raise MXNetError(
                "serving capture: MXNET_SERVING_CAPTURE_MB must be "
                "> 0, got %r (unset MXNET_SERVING_CAPTURE_DIR to "
                "disable capture)" % (capture_mb,))
        if os.path.exists(capture_dir) \
                and not os.path.isdir(capture_dir):
            raise MXNetError(
                "serving capture: capture_dir %r exists and is not a "
                "directory" % (capture_dir,))
        os.makedirs(capture_dir, exist_ok=True)
        path = os.path.join(capture_dir, "mx_capture_%d_%d.jsonl"
                            % (os.getpid(), next(_FILE_SEQ)))
        st = cls(path, int(float(capture_mb) * 2**20), header)
        st._t0 = t0
        return st

    @property
    def enabled(self):
        return self._f is not None

    def _write(self, rec, always=False):
        """Serialize + append one record. ``always`` exempts the
        header and retires of captured submits from the byte budget
        (see the module docstring). Returns False when the record was
        dropped at the budget.

        Capture failures never unwind the engine (flight-recorder /
        scrape-path precedent: observability must not kill serving):
        an unserializable record — e.g. a caller's ``np.int64``
        request id — is skipped and counted; an I/O error (disk full,
        file yanked) additionally DISABLES the stream, since every
        later write would fail the same way mid-submit/mid-drain."""
        try:
            line = json.dumps(rec, separators=(",", ":")) + "\n"
        except Exception as e:       # noqa: BLE001 — isolated
            with self._lock:
                self.skipped += 1
            _TM_SKIPPED.inc()
            _log.warning("serving capture: unserializable record "
                         "skipped (%s)", e)
            return False
        try:
            with self._lock:
                if self._f is None:
                    return False
                if not always and self.bytes_written + len(line) \
                        > self.max_bytes:
                    self.skipped += 1
                    _TM_SKIPPED.inc()
                    return False
                self._f.write(line)
                # flush per record: a SIGKILL'd process leaves every
                # completed line readable (the OS has the bytes; fsync
                # durability against machine crashes is not the
                # contract)
                self._f.flush()
                self.bytes_written += len(line)
        except OSError as e:
            _log.warning("serving capture: write failed (%s) — "
                         "capture disabled, %s is truncated at the "
                         "last whole record", e, self.path)
            self.close()
            return False
        _TM_RECORDS.inc()
        _TM_BYTES.set(self.bytes_written)
        return True

    def submit(self, req):
        """Record one accepted submit (called by the engine right
        after the request enters the queue)."""
        if self._f is None:
            return
        rec = {"kind": "submit",
               "t": round(req.t_submit - self._t0, 6),
               "id": req.id,
               "prompt": [int(x) for x in req.prompt],
               "max_tokens": int(req.max_tokens),
               "temperature": float(req.temperature),
               "seed": int(req.seed)}
        if req.eos_id is not None:
            rec["eos_id"] = int(req.eos_id)
        if req.deadline_ms is not None:
            rec["deadline_ms"] = float(req.deadline_ms)
        if req.ttft_deadline_ms is not None:
            rec["ttft_deadline_ms"] = float(req.ttft_deadline_ms)
        if req.resumed:
            rec["resume_tokens"] = list(req.tokens[:req.resumed])
        trace = getattr(req, "trace", None)
        if trace is not None:
            # fleet identity rides the capture so replay preserves it
            rec["trace_id"], rec["hop"] = trace
        if self._write(rec):
            with self._lock:
                self._captured.add(req.id)

    def retire(self, req):
        """Record one retirement — only for requests whose submit was
        captured (a retire without its submit is unreplayable noise).
        Carries the emitted tokens and the timings
        ``replay --verify`` byte-checks and latency-diffs against."""
        if self._f is None:
            return
        with self._lock:
            if req.id not in self._captured:
                return
            self._captured.discard(req.id)
        rec = {"kind": "retire",
               "t": round((req.t_done or req.t_submit) - self._t0, 6),
               "id": req.id,
               "reason": req.retire_reason,
               "tokens": [int(x) for x in req.tokens]}
        if req.t_first is not None:
            rec["ttft_ms"] = round(
                (req.t_first - req.t_submit) * 1e3, 3)
            if req.t_done is not None \
                    and len(req.tokens) - req.resumed > 1:
                rec["cadence_ms"] = round(
                    (req.t_done - req.t_first)
                    / (len(req.tokens) - req.resumed - 1) * 1e3, 3)
        self._write(rec, always=True)

    def close(self):
        """Flush and close the file (idempotent; a never-closed
        capture is still readable — every record was flushed)."""
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                finally:
                    self._f = None


def load_capture(path):
    """Parse a capture file into
    ``{"engine": geometry, "version": n, "submits": [...],
    "retires": {id: record}}``. Tolerates a torn final line (a crash
    mid-write leaves at most one partial record; every earlier line
    was flushed whole). Raises :class:`MXNetError` when the file has
    no header (not a capture)."""
    header = None
    submits = []
    retires = {}
    with open(path) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            # only the FINAL line may be torn (a crash mid-write);
            # garbage earlier means the file is not a capture
            if i == len(lines) - 1:
                break
            raise MXNetError(
                "capture %s: unparseable record at line %d "
                "(not a capture file?)" % (path, i + 1))
        kind = rec.get("kind")
        if header is None:
            if kind != "header" \
                    or rec.get("version") != CAPTURE_VERSION:
                raise MXNetError(
                    "capture %s: missing/unknown header (want a "
                    "version-%d mx_capture JSONL)"
                    % (path, CAPTURE_VERSION))
            header = rec
        elif kind == "submit":
            submits.append(rec)
        elif kind == "retire":
            retires[rec["id"]] = rec
    if header is None:
        raise MXNetError("capture %s: empty file" % path)
    return {"engine": header["engine"], "version": header["version"],
            "submits": submits, "retires": retires}
