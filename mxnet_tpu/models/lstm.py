"""LSTM language model built by explicit unrolling (reference
``example/rnn/lstm.py:17-107``): per-timestep FullyConnected i2h/h2h with
shared weight Variables, gates split with SliceChannel, per-step softmax
heads grouped into one Symbol. Works with the bucketing executor cache for
variable sequence lengths (SURVEY.md §2.5.6).

For long sequences the sequence-parallel path (``mxnet_tpu.parallel``)
is the TPU-native upgrade; this symbol version exists for reference parity
and for bucketing tests.
"""
from collections import namedtuple

from .. import symbol as sym

LSTMState = namedtuple("LSTMState", ["c", "h"])
LSTMParam = namedtuple("LSTMParam", ["i2h_weight", "i2h_bias",
                                     "h2h_weight", "h2h_bias"])


def lstm_cell(num_hidden, indata, prev_state, param, seqidx, layeridx,
              dropout=0.0):
    """One LSTM step (lstm.py:17-40): gates = i2h(x) + h2h(h); split 4-way
    → in/transform/forget/out."""
    if dropout > 0.0:
        indata = sym.Dropout(indata, p=dropout)
    i2h = sym.FullyConnected(indata, weight=param.i2h_weight,
                             bias=param.i2h_bias, num_hidden=num_hidden * 4,
                             name="t%d_l%d_i2h" % (seqidx, layeridx))
    h2h = sym.FullyConnected(prev_state.h, weight=param.h2h_weight,
                             bias=param.h2h_bias, num_hidden=num_hidden * 4,
                             name="t%d_l%d_h2h" % (seqidx, layeridx))
    gates = i2h + h2h
    sliced = sym.SliceChannel(gates, num_outputs=4,
                              name="t%d_l%d_slice" % (seqidx, layeridx))
    in_gate = sym.Activation(sliced[0], act_type="sigmoid")
    in_transform = sym.Activation(sliced[1], act_type="tanh")
    forget_gate = sym.Activation(sliced[2], act_type="sigmoid")
    out_gate = sym.Activation(sliced[3], act_type="sigmoid")
    next_c = (forget_gate * prev_state.c) + (in_gate * in_transform)
    next_h = out_gate * sym.Activation(next_c, act_type="tanh")
    return LSTMState(c=next_c, h=next_h)


def lstm_unroll(num_lstm_layer, seq_len, input_size, num_hidden, num_embed,
                num_label, dropout=0.0):
    """Unrolled LSTM LM (lstm.py:44-107). Inputs: ``data`` (batch, seq_len)
    int tokens, per-layer ``l%d_init_c/h``, label ``t%d_label`` per step.
    Returns a Group of per-step softmax heads."""
    embed_weight = sym.Variable("embed_weight")
    cls_weight = sym.Variable("cls_weight")
    cls_bias = sym.Variable("cls_bias")
    param_cells = []
    last_states = []
    for i in range(num_lstm_layer):
        param_cells.append(LSTMParam(
            i2h_weight=sym.Variable("l%d_i2h_weight" % i),
            i2h_bias=sym.Variable("l%d_i2h_bias" % i),
            h2h_weight=sym.Variable("l%d_h2h_weight" % i),
            h2h_bias=sym.Variable("l%d_h2h_bias" % i)))
        last_states.append(LSTMState(c=sym.Variable("l%d_init_c" % i),
                                     h=sym.Variable("l%d_init_h" % i)))

    data = sym.Variable("data")
    embed = sym.Embedding(data, weight=embed_weight, input_dim=input_size,
                          output_dim=num_embed, name="embed")
    wordvec = sym.SliceChannel(embed, num_outputs=seq_len, axis=1,
                               squeeze_axis=True, name="wordvec")

    outputs = []
    for t in range(seq_len):
        hidden = wordvec[t]
        for l in range(num_lstm_layer):
            dp = 0.0 if l == 0 else dropout
            state = lstm_cell(num_hidden, hidden, last_states[l],
                              param_cells[l], t, l, dropout=dp)
            hidden = state.h
            last_states[l] = state
        if dropout > 0.0:
            hidden = sym.Dropout(hidden, p=dropout)
        fc = sym.FullyConnected(hidden, weight=cls_weight, bias=cls_bias,
                                num_hidden=num_label,
                                name="t%d_cls" % t)
        label = sym.Variable("t%d_label" % t)
        outputs.append(sym.SoftmaxOutput(fc, label,
                                         name="t%d_sm" % t))
    return sym.Group(outputs)
