"""Transformer language model (decoder-only) — the long-context flagship.

No reference counterpart (the reference's sequence model is the unrolled
LSTM, example/rnn/lstm.py); this is the model family that exercises the
TPU framework's long-context machinery: flash attention (Pallas), ring
sequence parallelism (parallel/ring.py) and the dp/tp sharding rules.
Built entirely from registered Symbol ops so it trains through
FeedForward or ParallelTrainer like every other zoo model.
"""
from __future__ import annotations

import os

from .. import symbol as sym


def _ln(data, name):
    """LayerNorm site. ``MXNET_DIAG_IDENTITY_LN=1`` replaces every
    LayerNorm in the model with identity — a DIAGNOSTIC knob for the
    perf-attribution A/B (doc/performance.md: bounding the
    LN/elementwise share of the step) — never a training mode (the
    un-normalized model diverges)."""
    if os.environ.get("MXNET_DIAG_IDENTITY_LN", "0") == "1":
        return data
    return sym.LayerNorm(data=data,
                         gamma=sym.Variable(name + "_gamma"),
                         beta=sym.Variable(name + "_beta"),
                         name=name)

__all__ = ["transformer_block", "moe_transformer_block",
           "get_transformer_lm", "tp_rules", "ep_rules"]


def _attn_sublayer(data, num_heads, name, causal, impl, dropout,
                   rope=False, num_kv_heads=0, window=0):
    """x + MHA(LN(x)) then LN — the shared attention half of a block."""
    ln1 = _ln(data, name + "_ln1")
    attn = sym.MultiHeadAttention(
        data=ln1,
        qkv_weight=sym.Variable(name + "_qkv_weight"),
        qkv_bias=sym.Variable(name + "_qkv_bias"),
        out_weight=sym.Variable(name + "_proj_weight"),
        out_bias=sym.Variable(name + "_proj_bias"),
        num_heads=num_heads, num_kv_heads=num_kv_heads, causal=causal,
        impl=impl, dropout=dropout, rope=rope, window=window,
        name=name + "_attn")
    x = data + attn
    ln2 = _ln(x, name + "_ln2")
    return x, ln2


def transformer_block(data, num_heads, hidden, embed_dim, name,
                      causal=True, impl="flash", dropout=0.0,
                      rope=False, num_kv_heads=0, window=0):
    """Pre-LN block: x + MHA(LN(x)); x + FFN(LN(x)). data: [B,T,E]."""
    x, ln2 = _attn_sublayer(data, num_heads, name, causal, impl, dropout,
                            rope=rope, num_kv_heads=num_kv_heads,
                            window=window)
    f1 = sym.FullyConnected(data=ln2, num_hidden=hidden,
                            name=name + "_ffn1", flatten=False)
    act = sym.Activation(data=f1, act_type="relu", name=name + "_ffn_relu")
    f2 = sym.FullyConnected(data=act, num_hidden=embed_dim,
                            name=name + "_ffn2", flatten=False)
    return x + f2


def moe_transformer_block(data, num_heads, hidden, embed_dim, num_experts,
                          name, causal=True, impl="flash", dropout=0.0,
                          moe_top_k=0, rope=False, num_kv_heads=0,
                          window=0):
    """Transformer block whose FFN is a mixture of experts (MoEFFN):
    shard the expert dim over ``ep`` (ep_rules) for expert parallelism.
    ``moe_top_k>0`` enables static-shaped top-k hard routing."""
    x, ln2 = _attn_sublayer(data, num_heads, name, causal, impl, dropout,
                            rope=rope, num_kv_heads=num_kv_heads,
                            window=window)
    moe = sym.MoEFFN(
        data=ln2,
        gate_weight=sym.Variable(name + "_gate_weight"),
        expert_w1=sym.Variable(name + "_expert_w1"),
        expert_b1=sym.Variable(name + "_expert_b1"),
        expert_w2=sym.Variable(name + "_expert_w2"),
        expert_b2=sym.Variable(name + "_expert_b2"),
        num_experts=num_experts, hidden=hidden, top_k=moe_top_k,
        name=name + "_moe")
    return x + moe


def get_transformer_lm(vocab_size, num_layers=2, embed_dim=128, num_heads=4,
                       ffn_hidden=None, seq_len=None, impl="flash",
                       dropout=0.0, num_experts=0, pipeline_stages=None,
                       moe_top_k=0, loss_layout="reference",
                       pos_encoding="learned", num_kv_heads=0,
                       window=0):
    """Decoder-only LM: Embedding -> N blocks -> tied-free FC -> softmax
    over vocab per position (multi_output SoftmaxOutput, the reference's
    per-position softmax mode, softmax_output-inl.h multi_output).

    ``pipeline_stages=S`` tags every node with ``ctx_group='stage<K>'``
    (the reference's model-parallel graph-cut attribute,
    graph_executor.cc:341-458): embedding with the first block group,
    final LN + head + loss with the last; blocks spread evenly. The
    tagged symbol drives ``parallel.PipelineTrainer``.

    ``loss_layout``: "reference" (default) swaps the [B,T,V] logits to
    [B,V,T] and uses the reference's multi_output per-position softmax
    (output [B,V,T]). "flat" reshapes to [B*T,V] and applies the plain
    softmax along the LAST (lane-aligned) axis — identical loss and
    gradients without transposing the vocab-sized logits tensor
    (output [B*T,V]). "ce" ends in the fused ``SoftmaxCELoss`` head:
    the output is the per-token LOSS [B*T] (f32) and the vocab-sized
    probability tensor is never materialized — identical parameter
    updates (the loss gradient is SoftmaxOutput's), but consumers that
    need probabilities (accuracy metrics, predict) should use the other
    layouts.

    ``pos_encoding``: "learned" (default) adds the trained absolute
    pos_embed table; "rope" rotates q/k inside every attention instead
    (rotary/RoFormer — relative positions, no table, so decoding is not
    bounded by a trained length).

    ``num_kv_heads`` (0 = ``num_heads``): grouped-query attention —
    K/V projected to fewer heads, shrinking the decoder's K/V cache by
    the group factor (see MultiHeadAttention).

    ``window`` (0 = unlimited): sliding-window attention in every
    block; the decode cache becomes an O(window) ring buffer (pair
    with ``pos_encoding="rope"`` for unbounded-length generation).
    """
    from ..attribute import AttrScope

    if pos_encoding not in ("learned", "rope"):
        raise ValueError("pos_encoding must be 'learned' or 'rope', "
                         "got %r" % (pos_encoding,))
    if loss_layout not in ("reference", "flat", "ce"):
        raise ValueError("loss_layout must be 'reference', 'flat' or "
                         "'ce', got %r" % (loss_layout,))
    if ffn_hidden is None:
        ffn_hidden = 4 * embed_dim

    def scope(i=None, last=False):
        if not pipeline_stages:
            return AttrScope()
        if last:
            s = pipeline_stages - 1
        else:
            s = 0 if i is None else i * pipeline_stages // num_layers
        return AttrScope(ctx_group="stage%d" % s)

    with scope(0):
        data = sym.Variable("data")  # [B, T] int tokens
        net = sym.Embedding(data=data, input_dim=vocab_size,
                            output_dim=embed_dim, name="embed")
        rope = pos_encoding == "rope"
        if not rope:
            # learned additive positional embedding, rows sharded with
            # their positions under sequence parallelism
            net = sym.PositionalEmbedding(data=net,
                                          pos=sym.Variable("pos_embed"),
                                          name="pos_add")
    for i in range(num_layers):
        with scope(i):
            if num_experts:
                net = moe_transformer_block(net, num_heads, ffn_hidden,
                                            embed_dim, num_experts,
                                            "layer%d" % i, impl=impl,
                                            dropout=dropout,
                                            moe_top_k=moe_top_k,
                                            rope=rope,
                                            num_kv_heads=num_kv_heads,
                                            window=window)
            else:
                net = transformer_block(net, num_heads, ffn_hidden,
                                        embed_dim, "layer%d" % i,
                                        impl=impl, dropout=dropout,
                                        rope=rope,
                                        num_kv_heads=num_kv_heads,
                                        window=window)
    with scope(last=True):
        ln_f = _ln(net, "lnf")
        logits = sym.FullyConnected(data=ln_f, num_hidden=vocab_size,
                                    name="lm_head", flatten=False)
        if loss_layout in ("flat", "ce"):
            flat = sym.Reshape(data=logits, shape=(-1, vocab_size),
                               name="logits_flat")
            flat_label = sym.Reshape(
                data=sym.Variable("softmax_label"), shape=(-1,),
                name="label_flat")
            if loss_layout == "ce":
                return sym.SoftmaxCELoss(data=flat, label=flat_label,
                                         name="softmax")
            return sym.SoftmaxOutput(data=flat, label=flat_label,
                                     name="softmax")
        # per-position softmax: label [B, T]
        logits_t = sym.SwapAxis(data=logits, dim1=1, dim2=2,
                                name="logits_t")
        return sym.SoftmaxOutput(data=logits_t, name="softmax",
                                 multi_output=True)


def tp_rules():
    """Tensor-parallel sharding rules for transformer params (Megatron
    layout: QKV/FFN1 column-parallel, proj/FFN2 row-parallel) — pass to
    ShardingRules(param_rules=...)."""
    from ..parallel.shard import P
    return [
        (r"_qkv_weight$", P("tp", None)),
        (r"_qkv_bias$", P("tp")),
        (r"_ffn1_weight$", P("tp", None)),
        (r"_ffn1_bias$", P("tp")),
        (r"_proj_weight$", P(None, "tp")),
        (r"_ffn2_weight$", P(None, "tp")),
        (r"embed_weight$", P("tp", None)),
        (r"lm_head_weight$", P("tp", None)),
    ]


def ep_rules():
    """Expert-parallel sharding rules: the leading num_experts dim of
    every MoEFFN parameter shards over ``ep``; XLA inserts the psum over
    ``ep`` for the gate-weighted combine."""
    from ..parallel.shard import P
    return [
        (r"_expert_w1$", P("ep", None, None)),
        (r"_expert_b1$", P("ep", None)),
        (r"_expert_w2$", P("ep", None, None)),
        (r"_expert_b2$", P("ep", None)),
    ]
