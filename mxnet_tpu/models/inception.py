"""Inception family.

* :func:`get_inception_bn_small` — the CIFAR-10 net benchmarked in the
  reference README (``symbol_inception-bn-28-small.py``; BASELINE.md's
  842 img/s headline row).
* :func:`get_inception_bn` — BN-Inception for ImageNet
  (``symbol_inception-bn.py`` / ``-full.py``; Ioffe & Szegedy 2015).
* :func:`get_googlenet` — original GoogLeNet (``symbol_googlenet.py``).
* :func:`get_inception_v3` — factorized-conv Inception
  (``symbol_inception-v3.py``; Szegedy et al. 2015).

Widths follow the published papers; concat-heavy graphs are a good XLA
stress test (the reference needed its graph allocator's sharing logic for
these — here buffer assignment handles it).
"""
from .. import symbol as sym


def conv_factory(data, num_filter, kernel, stride=(1, 1), pad=(0, 0),
                 name=None, with_bn=True, act_type="relu"):
    """Conv → (BN) → ReLU block, the unit every Inception variant builds
    from (reference ConvFactory)."""
    c = sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, no_bias=with_bn,
                        name=None if name is None else name + "_conv")
    if with_bn:
        c = sym.BatchNorm(c, fix_gamma=False,
                          name=None if name is None else name + "_bn")
    return sym.Activation(c, act_type=act_type,
                          name=None if name is None else name + "_relu")


# ---------------------------------------------------------------------------
# CIFAR-10 inception-bn-28-small
def _simple_module(data, ch_1x1, ch_3x3, name):
    b1 = conv_factory(data, ch_1x1, (1, 1), name=name + "_1x1")
    b3 = conv_factory(data, ch_3x3, (3, 3), pad=(1, 1), name=name + "_3x3")
    return sym.Concat(b1, b3, name=name + "_concat")


def _downsample_module(data, ch_3x3, name):
    b3 = conv_factory(data, ch_3x3, (3, 3), stride=(2, 2), pad=(1, 1),
                      name=name + "_3x3")
    pool = sym.Pooling(data, kernel=(3, 3), stride=(2, 2), pool_type="max",
                       name=name + "_pool")
    return sym.Concat(b3, pool, name=name + "_concat")


def get_inception_bn_small(num_classes=10):
    data = sym.Variable("data")
    net = conv_factory(data, 96, (3, 3), pad=(1, 1), name="conv1")
    net = _simple_module(net, 32, 32, "in3a")
    net = _simple_module(net, 32, 48, "in3b")
    net = _downsample_module(net, 80, "in3c")
    net = _simple_module(net, 112, 48, "in4a")
    net = _simple_module(net, 96, 64, "in4b")
    net = _simple_module(net, 80, 80, "in4c")
    net = _simple_module(net, 48, 96, "in4d")
    net = _downsample_module(net, 96, "in4e")
    net = _simple_module(net, 176, 160, "in5a")
    net = _simple_module(net, 176, 160, "in5b")
    net = sym.Pooling(net, pool_type="avg", kernel=(1, 1), global_pool=True,
                      name="global_pool")
    net = sym.Flatten(net, name="flatten1")
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(net, name="softmax")


# ---------------------------------------------------------------------------
# BN-Inception (ImageNet)
def _inception_bn_module(data, f1, f3r, f3, fd3r, fd3, proj, pool, name):
    branches = []
    if f1:
        branches.append(conv_factory(data, f1, (1, 1), name=name + "_1x1"))
    b3 = conv_factory(data, f3r, (1, 1), name=name + "_3x3r")
    branches.append(conv_factory(b3, f3, (3, 3), pad=(1, 1),
                                 name=name + "_3x3"))
    bd = conv_factory(data, fd3r, (1, 1), name=name + "_d3x3r")
    bd = conv_factory(bd, fd3, (3, 3), pad=(1, 1), name=name + "_d3x3a")
    branches.append(conv_factory(bd, fd3, (3, 3), pad=(1, 1),
                                 name=name + "_d3x3b"))
    p = sym.Pooling(data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                    pool_type=pool, name=name + "_pool")
    branches.append(conv_factory(p, proj, (1, 1), name=name + "_proj"))
    return sym.Concat(*branches, name=name + "_concat")


def _inception_bn_downsample(data, f3r, f3, fd3r, fd3, name):
    b3 = conv_factory(data, f3r, (1, 1), name=name + "_3x3r")
    b3 = conv_factory(b3, f3, (3, 3), stride=(2, 2), pad=(1, 1),
                      name=name + "_3x3")
    bd = conv_factory(data, fd3r, (1, 1), name=name + "_d3x3r")
    bd = conv_factory(bd, fd3, (3, 3), pad=(1, 1), name=name + "_d3x3a")
    bd = conv_factory(bd, fd3, (3, 3), stride=(2, 2), pad=(1, 1),
                      name=name + "_d3x3b")
    p = sym.Pooling(data, kernel=(3, 3), stride=(2, 2), pool_type="max",
                    name=name + "_pool")
    return sym.Concat(b3, bd, p, name=name + "_concat")


def get_inception_bn(num_classes=1000):
    data = sym.Variable("data")
    net = conv_factory(data, 64, (7, 7), stride=(2, 2), pad=(3, 3),
                       name="conv1")
    net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                      name="pool1")
    net = conv_factory(net, 64, (1, 1), name="conv2r")
    net = conv_factory(net, 192, (3, 3), pad=(1, 1), name="conv2")
    net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                      name="pool2")
    net = _inception_bn_module(net, 64, 64, 64, 64, 96, 32, "avg", "in3a")
    net = _inception_bn_module(net, 64, 64, 96, 64, 96, 64, "avg", "in3b")
    net = _inception_bn_downsample(net, 128, 160, 64, 96, "in3c")
    net = _inception_bn_module(net, 224, 64, 96, 96, 128, 128, "avg", "in4a")
    net = _inception_bn_module(net, 192, 96, 128, 96, 128, 128, "avg", "in4b")
    net = _inception_bn_module(net, 160, 128, 160, 128, 160, 96, "avg",
                               "in4c")
    net = _inception_bn_module(net, 96, 128, 192, 160, 192, 96, "avg", "in4d")
    net = _inception_bn_downsample(net, 128, 192, 192, 256, "in4e")
    net = _inception_bn_module(net, 352, 192, 320, 160, 224, 128, "avg",
                               "in5a")
    net = _inception_bn_module(net, 352, 192, 320, 192, 224, 128, "max",
                               "in5b")
    net = sym.Pooling(net, kernel=(1, 1), global_pool=True, pool_type="avg",
                      name="global_pool")
    net = sym.Flatten(net)
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(net, name="softmax")


# ---------------------------------------------------------------------------
# GoogLeNet (Inception v1, no BN)
def _googlenet_module(data, f1, f3r, f3, f5r, f5, proj, name):
    b1 = conv_factory(data, f1, (1, 1), name=name + "_1x1", with_bn=False)
    b3 = conv_factory(data, f3r, (1, 1), name=name + "_3x3r", with_bn=False)
    b3 = conv_factory(b3, f3, (3, 3), pad=(1, 1), name=name + "_3x3",
                      with_bn=False)
    b5 = conv_factory(data, f5r, (1, 1), name=name + "_5x5r", with_bn=False)
    b5 = conv_factory(b5, f5, (5, 5), pad=(2, 2), name=name + "_5x5",
                      with_bn=False)
    p = sym.Pooling(data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                    pool_type="max", name=name + "_pool")
    p = conv_factory(p, proj, (1, 1), name=name + "_proj", with_bn=False)
    return sym.Concat(b1, b3, b5, p, name=name + "_concat")


def get_googlenet(num_classes=1000):
    data = sym.Variable("data")
    net = conv_factory(data, 64, (7, 7), stride=(2, 2), pad=(3, 3),
                       name="conv1", with_bn=False)
    net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                      name="pool1")
    net = conv_factory(net, 64, (1, 1), name="conv2r", with_bn=False)
    net = conv_factory(net, 192, (3, 3), pad=(1, 1), name="conv2",
                       with_bn=False)
    net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                      name="pool2")
    net = _googlenet_module(net, 64, 96, 128, 16, 32, 32, "in3a")
    net = _googlenet_module(net, 128, 128, 192, 32, 96, 64, "in3b")
    net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                      name="pool3")
    net = _googlenet_module(net, 192, 96, 208, 16, 48, 64, "in4a")
    net = _googlenet_module(net, 160, 112, 224, 24, 64, 64, "in4b")
    net = _googlenet_module(net, 128, 128, 256, 24, 64, 64, "in4c")
    net = _googlenet_module(net, 112, 144, 288, 32, 64, 64, "in4d")
    net = _googlenet_module(net, 256, 160, 320, 32, 128, 128, "in4e")
    net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                      name="pool4")
    net = _googlenet_module(net, 256, 160, 320, 32, 128, 128, "in5a")
    net = _googlenet_module(net, 384, 192, 384, 48, 128, 128, "in5b")
    net = sym.Pooling(net, kernel=(1, 1), global_pool=True, pool_type="avg",
                      name="global_pool")
    net = sym.Flatten(net)
    net = sym.Dropout(net, p=0.4)
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(net, name="softmax")


# ---------------------------------------------------------------------------
# Inception-v3
def _inception_a(data, pool_proj, name):
    b1 = conv_factory(data, 64, (1, 1), name=name + "_1x1")
    b5 = conv_factory(data, 48, (1, 1), name=name + "_5x5r")
    b5 = conv_factory(b5, 64, (5, 5), pad=(2, 2), name=name + "_5x5")
    b3 = conv_factory(data, 64, (1, 1), name=name + "_d3x3r")
    b3 = conv_factory(b3, 96, (3, 3), pad=(1, 1), name=name + "_d3x3a")
    b3 = conv_factory(b3, 96, (3, 3), pad=(1, 1), name=name + "_d3x3b")
    p = sym.Pooling(data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                    pool_type="avg", name=name + "_pool")
    p = conv_factory(p, pool_proj, (1, 1), name=name + "_proj")
    return sym.Concat(b1, b5, b3, p, name=name + "_concat")


def _reduction_a(data, name):
    b3 = conv_factory(data, 384, (3, 3), stride=(2, 2), name=name + "_3x3")
    bd = conv_factory(data, 64, (1, 1), name=name + "_d3x3r")
    bd = conv_factory(bd, 96, (3, 3), pad=(1, 1), name=name + "_d3x3a")
    bd = conv_factory(bd, 96, (3, 3), stride=(2, 2), name=name + "_d3x3b")
    p = sym.Pooling(data, kernel=(3, 3), stride=(2, 2), pool_type="max",
                    name=name + "_pool")
    return sym.Concat(b3, bd, p, name=name + "_concat")


def _inception_b(data, c7, name):
    b1 = conv_factory(data, 192, (1, 1), name=name + "_1x1")
    b7 = conv_factory(data, c7, (1, 1), name=name + "_7x7r")
    b7 = conv_factory(b7, c7, (1, 7), pad=(0, 3), name=name + "_1x7a")
    b7 = conv_factory(b7, 192, (7, 1), pad=(3, 0), name=name + "_7x1a")
    bd = conv_factory(data, c7, (1, 1), name=name + "_d7r")
    bd = conv_factory(bd, c7, (7, 1), pad=(3, 0), name=name + "_7x1b")
    bd = conv_factory(bd, c7, (1, 7), pad=(0, 3), name=name + "_1x7b")
    bd = conv_factory(bd, c7, (7, 1), pad=(3, 0), name=name + "_7x1c")
    bd = conv_factory(bd, 192, (1, 7), pad=(0, 3), name=name + "_1x7c")
    p = sym.Pooling(data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                    pool_type="avg", name=name + "_pool")
    p = conv_factory(p, 192, (1, 1), name=name + "_proj")
    return sym.Concat(b1, b7, bd, p, name=name + "_concat")


def _reduction_b(data, name):
    b3 = conv_factory(data, 192, (1, 1), name=name + "_3x3r")
    b3 = conv_factory(b3, 320, (3, 3), stride=(2, 2), name=name + "_3x3")
    b7 = conv_factory(data, 192, (1, 1), name=name + "_7x7r")
    b7 = conv_factory(b7, 192, (1, 7), pad=(0, 3), name=name + "_1x7")
    b7 = conv_factory(b7, 192, (7, 1), pad=(3, 0), name=name + "_7x1")
    b7 = conv_factory(b7, 192, (3, 3), stride=(2, 2), name=name + "_3x3b")
    p = sym.Pooling(data, kernel=(3, 3), stride=(2, 2), pool_type="max",
                    name=name + "_pool")
    return sym.Concat(b3, b7, p, name=name + "_concat")


def _inception_c(data, name):
    b1 = conv_factory(data, 320, (1, 1), name=name + "_1x1")
    b3 = conv_factory(data, 384, (1, 1), name=name + "_3x3r")
    b3a = conv_factory(b3, 384, (1, 3), pad=(0, 1), name=name + "_1x3")
    b3b = conv_factory(b3, 384, (3, 1), pad=(1, 0), name=name + "_3x1")
    bd = conv_factory(data, 448, (1, 1), name=name + "_d3r")
    bd = conv_factory(bd, 384, (3, 3), pad=(1, 1), name=name + "_d3")
    bda = conv_factory(bd, 384, (1, 3), pad=(0, 1), name=name + "_d1x3")
    bdb = conv_factory(bd, 384, (3, 1), pad=(1, 0), name=name + "_d3x1")
    p = sym.Pooling(data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                    pool_type="avg", name=name + "_pool")
    p = conv_factory(p, 192, (1, 1), name=name + "_proj")
    return sym.Concat(b1, b3a, b3b, bda, bdb, p, name=name + "_concat")


def get_inception_v3(num_classes=1000):
    """Input NCHW 3x299x299."""
    data = sym.Variable("data")
    net = conv_factory(data, 32, (3, 3), stride=(2, 2), name="conv1")
    net = conv_factory(net, 32, (3, 3), name="conv2")
    net = conv_factory(net, 64, (3, 3), pad=(1, 1), name="conv3")
    net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                      name="pool1")
    net = conv_factory(net, 80, (1, 1), name="conv4")
    net = conv_factory(net, 192, (3, 3), name="conv5")
    net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                      name="pool2")
    net = _inception_a(net, 32, "in_a1")
    net = _inception_a(net, 64, "in_a2")
    net = _inception_a(net, 64, "in_a3")
    net = _reduction_a(net, "red_a")
    net = _inception_b(net, 128, "in_b1")
    net = _inception_b(net, 160, "in_b2")
    net = _inception_b(net, 160, "in_b3")
    net = _inception_b(net, 192, "in_b4")
    net = _reduction_b(net, "red_b")
    net = _inception_c(net, "in_c1")
    net = _inception_c(net, "in_c2")
    net = sym.Pooling(net, kernel=(1, 1), global_pool=True, pool_type="avg",
                      name="global_pool")
    net = sym.Flatten(net)
    net = sym.Dropout(net, p=0.5)
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(net, name="softmax")
