"""Model zoo: symbol constructors for the reference's example model families.

Parity map (reference ``example/``):

* ``example/image-classification/train_mnist.py`` nets  -> :mod:`.classifiers`
* ``symbol_alexnet.py``                                 -> :mod:`.alexnet`
* ``symbol_vgg.py``                                     -> :mod:`.vgg`
* ``symbol_resnet-28-small.py`` (+ modern ImageNet
  ResNets, the BASELINE.json north-star model)          -> :mod:`.resnet`
* ``symbol_inception-bn-28-small.py``, ``symbol_inception-bn.py``,
  ``symbol_inception-bn-full.py``, ``symbol_inception-v3.py``,
  ``symbol_googlenet.py``                               -> :mod:`.inception`
* ``example/rnn/lstm.py`` (unroll + bucketing)          -> :mod:`.lstm`
* ``example/fcn-xs/symbol_fcnxs.py``                    -> :mod:`.fcn`

Every constructor returns a :class:`mxnet_tpu.symbol.Symbol` whose single
head is a ``SoftmaxOutput`` (classification) so it drops straight into
``FeedForward``/``fit``. ``get_symbol(name, **kw)`` mirrors the reference's
``train_model.py --network`` dispatch.
"""
from . import classifiers, alexnet, vgg, resnet, inception, lstm, fcn
from .classifiers import get_mlp, get_lenet
from .alexnet import get_alexnet
from .vgg import get_vgg
from .resnet import (get_resnet, get_resnet_cifar,
                     convert_stem_weight_s2d,
                     space_to_depth_batch)
from .inception import (get_inception_bn_small, get_inception_bn,
                        get_inception_v3, get_googlenet)
from .lstm import lstm_unroll, LSTMState, LSTMParam
from .fcn import get_fcn_symbol
from . import transformer
from .transformer import (get_transformer_lm, transformer_block,
                          moe_transformer_block)

_REGISTRY = {
    "mlp": get_mlp,
    "lenet": get_lenet,
    "alexnet": get_alexnet,
    "vgg": get_vgg,
    "resnet": get_resnet,
    "resnet-28-small": get_resnet_cifar,
    "inception-bn-28-small": get_inception_bn_small,
    "inception-bn": get_inception_bn,
    "inception-v3": get_inception_v3,
    "googlenet": get_googlenet,
    "fcn-xs": get_fcn_symbol,
}


def get_symbol(name, **kwargs):
    """Construct a model symbol by name (``train_model.py --network``)."""
    if name not in _REGISTRY:
        raise ValueError("unknown network %r (have: %s)"
                         % (name, ", ".join(sorted(_REGISTRY))))
    return _REGISTRY[name](**kwargs)
