"""AlexNet (reference ``symbol_alexnet.py``; Krizhevsky et al. 2012,
single-tower variant). Exercises LRN, grouped-free large convs, dropout."""
from .. import symbol as sym


def get_alexnet(num_classes=1000):
    data = sym.Variable("data")
    # stage 1
    c1 = sym.Convolution(data, kernel=(11, 11), stride=(4, 4), num_filter=96)
    r1 = sym.Activation(c1, act_type="relu")
    p1 = sym.Pooling(r1, pool_type="max", kernel=(3, 3), stride=(2, 2))
    n1 = sym.LRN(p1, nsize=5, alpha=1e-4, beta=0.75)
    # stage 2
    c2 = sym.Convolution(n1, kernel=(5, 5), pad=(2, 2), num_filter=256)
    r2 = sym.Activation(c2, act_type="relu")
    p2 = sym.Pooling(r2, pool_type="max", kernel=(3, 3), stride=(2, 2))
    n2 = sym.LRN(p2, nsize=5, alpha=1e-4, beta=0.75)
    # stage 3: three 3x3 convs
    c3 = sym.Convolution(n2, kernel=(3, 3), pad=(1, 1), num_filter=384)
    r3 = sym.Activation(c3, act_type="relu")
    c4 = sym.Convolution(r3, kernel=(3, 3), pad=(1, 1), num_filter=384)
    r4 = sym.Activation(c4, act_type="relu")
    c5 = sym.Convolution(r4, kernel=(3, 3), pad=(1, 1), num_filter=256)
    r5 = sym.Activation(c5, act_type="relu")
    p3 = sym.Pooling(r5, pool_type="max", kernel=(3, 3), stride=(2, 2))
    # classifier
    fl = sym.Flatten(p3)
    f1 = sym.FullyConnected(fl, num_hidden=4096)
    r6 = sym.Activation(f1, act_type="relu")
    d1 = sym.Dropout(r6, p=0.5)
    f2 = sym.FullyConnected(d1, num_hidden=4096)
    r7 = sym.Activation(f2, act_type="relu")
    d2 = sym.Dropout(r7, p=0.5)
    f3 = sym.FullyConnected(d2, num_hidden=num_classes)
    return sym.SoftmaxOutput(f3, name="softmax")
