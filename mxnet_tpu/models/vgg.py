"""VGG-11/13/16/19 (reference ``symbol_vgg.py`` is the 16-layer net;
Simonyan & Zisserman 2014). ``num_layers`` selects the config."""
from .. import symbol as sym

_CONFIGS = {
    11: ((1, 64), (1, 128), (2, 256), (2, 512), (2, 512)),
    13: ((2, 64), (2, 128), (2, 256), (2, 512), (2, 512)),
    16: ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512)),
    19: ((2, 64), (2, 128), (4, 256), (4, 512), (4, 512)),
}


def vgg_backbone(data, num_layers=16, with_pool5=True):
    """Conv body shared with FCN (fcn.py builds its skip heads off the
    stage outputs). Returns (net, stage_outputs)."""
    stages = []
    net = data
    for si, (reps, filters) in enumerate(_CONFIGS[num_layers], start=1):
        for ri in range(reps):
            net = sym.Convolution(net, kernel=(3, 3), pad=(1, 1),
                                  num_filter=filters,
                                  name="conv%d_%d" % (si, ri + 1))
            net = sym.Activation(net, act_type="relu",
                                 name="relu%d_%d" % (si, ri + 1))
        if si < 5 or with_pool5:
            net = sym.Pooling(net, pool_type="max", kernel=(2, 2),
                              stride=(2, 2), name="pool%d" % si)
        stages.append(net)
    return net, stages


def get_vgg(num_classes=1000, num_layers=16):
    data = sym.Variable("data")
    net, _ = vgg_backbone(data, num_layers)
    fl = sym.Flatten(net)
    f6 = sym.FullyConnected(fl, num_hidden=4096, name="fc6")
    r6 = sym.Activation(f6, act_type="relu")
    d6 = sym.Dropout(r6, p=0.5)
    f7 = sym.FullyConnected(d6, num_hidden=4096, name="fc7")
    r7 = sym.Activation(f7, act_type="relu")
    d7 = sym.Dropout(r7, p=0.5)
    f8 = sym.FullyConnected(d7, num_hidden=num_classes, name="fc8")
    return sym.SoftmaxOutput(f8, name="softmax")
