"""FCN-32s/16s/8s semantic segmentation (reference
``example/fcn-xs/symbol_fcnxs.py``; Long et al. 2015). VGG-16 backbone with
convolutionalized fc6/fc7, per-stage score heads, Deconvolution upsampling,
Crop-to-reference skip fusion, and a multi_output SoftmaxOutput over the
class-score map. Exercises Deconvolution + Crop + large activations
(BASELINE.json config 5).

TPU note: the reference pads conv1_1 by 100px so one graph handles any
input size; under XLA shapes are static per bind anyway, so we keep the
classic padding scheme purely for offset parity — bucketed binds handle
multiple sizes.
"""
from .. import symbol as sym


def _vgg_stage(net, reps, filters, si, first_pad=(1, 1)):
    for ri in range(reps):
        pad = first_pad if (si == 1 and ri == 0) else (1, 1)
        net = sym.Convolution(net, kernel=(3, 3), pad=pad,
                              num_filter=filters,
                              name="conv%d_%d" % (si, ri + 1))
        net = sym.Activation(net, act_type="relu",
                             name="relu%d_%d" % (si, ri + 1))
    return sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2),
                       name="pool%d" % si)


def _score_head(net, num_classes, name):
    return sym.Convolution(net, kernel=(1, 1), num_filter=num_classes,
                           name=name)


def get_fcn_symbol(num_classes=21, variant="32s"):
    """Build FCN-``variant`` (one of "32s", "16s", "8s")."""
    if variant not in ("32s", "16s", "8s"):
        raise ValueError("variant must be 32s/16s/8s, got %r" % (variant,))
    data = sym.Variable("data")
    net = _vgg_stage(data, 2, 64, 1, first_pad=(100, 100))
    net = _vgg_stage(net, 2, 128, 2)
    pool3 = _vgg_stage(net, 3, 256, 3)
    pool4 = _vgg_stage(pool3, 3, 512, 4)
    net = _vgg_stage(pool4, 3, 512, 5)
    # convolutionalized classifier head
    net = sym.Convolution(net, kernel=(7, 7), num_filter=4096, name="fc6")
    net = sym.Activation(net, act_type="relu", name="relu6")
    net = sym.Dropout(net, p=0.5, name="drop6")
    net = sym.Convolution(net, kernel=(1, 1), num_filter=4096, name="fc7")
    net = sym.Activation(net, act_type="relu", name="relu7")
    net = sym.Dropout(net, p=0.5, name="drop7")
    score = _score_head(net, num_classes, "score")

    if variant == "32s":
        up = sym.Deconvolution(score, kernel=(64, 64), stride=(32, 32),
                               num_filter=num_classes, no_bias=True,
                               name="upscore32")
        out = sym.Crop(up, data, num_args=2, offset=(19, 19), name="crop32")
    else:
        score2 = sym.Deconvolution(score, kernel=(4, 4), stride=(2, 2),
                                   num_filter=num_classes, no_bias=True,
                                   name="score2")
        sp4 = _score_head(pool4, num_classes, "score_pool4")
        sp4c = sym.Crop(sp4, score2, num_args=2, offset=(5, 5),
                        name="score_pool4c")
        fuse4 = score2 + sp4c
        if variant == "16s":
            up = sym.Deconvolution(fuse4, kernel=(32, 32), stride=(16, 16),
                                   num_filter=num_classes, no_bias=True,
                                   name="upscore16")
            out = sym.Crop(up, data, num_args=2, offset=(27, 27),
                           name="crop16")
        else:
            score4 = sym.Deconvolution(fuse4, kernel=(4, 4), stride=(2, 2),
                                       num_filter=num_classes, no_bias=True,
                                       name="score4")
            sp3 = _score_head(pool3, num_classes, "score_pool3")
            sp3c = sym.Crop(sp3, score4, num_args=2, offset=(9, 9),
                            name="score_pool3c")
            fuse3 = score4 + sp3c
            up = sym.Deconvolution(fuse3, kernel=(16, 16), stride=(8, 8),
                                   num_filter=num_classes, no_bias=True,
                                   name="upscore8")
            out = sym.Crop(up, data, num_args=2, offset=(31, 31),
                           name="crop8")
    return sym.SoftmaxOutput(out, multi_output=True, use_ignore=True,
                             ignore_label=255, name="softmax")
