"""ResNets (He et al. 2015).

* :func:`get_resnet_cifar` — the 6n+2 CIFAR net (reference
  ``symbol_resnet-28-small.py``: conv3x3-16 stem, three stages of n
  residual units at 16/32/64 filters, global-avg-pool, fc).
* :func:`get_resnet` — ImageNet ResNet-18/34/50/101/152. ResNet-50 is the
  BASELINE.json north-star benchmark model, so this is the framework's
  flagship: bench.py and __graft_entry__ build it through this function.

TPU notes: all convs are NCHW symbols lowered to ``lax.conv_general_dilated``
— XLA lays them out for the MXU and fuses the BN+ReLU chains into the conv
epilogues, which is exactly the fusion the reference needed cuDNN for.
"""
from .. import symbol as sym


def _conv_bn(data, num_filter, kernel, stride, pad, name, act=True,
             eps=2e-5, momentum=0.9):
    c = sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, no_bias=True,
                        name=name + "_conv")
    b = sym.BatchNorm(c, eps=eps, momentum=momentum, fix_gamma=False,
                      name=name + "_bn")
    if act:
        return sym.Activation(b, act_type="relu", name=name + "_relu")
    return b


def residual_unit(data, num_filter, stride, dim_match, name,
                  bottleneck=True):
    """Post-activation residual unit (v1). ``dim_match=False`` projects the
    shortcut with a strided 1x1 conv+BN."""
    if bottleneck:
        mid = num_filter // 4
        body = _conv_bn(data, mid, (1, 1), (1, 1), (0, 0), name + "_a")
        body = _conv_bn(body, mid, (3, 3), stride, (1, 1), name + "_b")
        body = _conv_bn(body, num_filter, (1, 1), (1, 1), (0, 0),
                        name + "_c", act=False)
    else:
        body = _conv_bn(data, num_filter, (3, 3), stride, (1, 1),
                        name + "_a")
        body = _conv_bn(body, num_filter, (3, 3), (1, 1), (1, 1),
                        name + "_b", act=False)
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv_bn(data, num_filter, (1, 1), stride, (0, 0),
                            name + "_sc", act=False)
    return sym.Activation(body + shortcut, act_type="relu",
                          name=name + "_out")


_UNITS = {
    18: ([2, 2, 2, 2], False),
    34: ([3, 4, 6, 3], False),
    50: ([3, 4, 6, 3], True),
    101: ([3, 4, 23, 3], True),
    152: ([3, 8, 36, 3], True),
}


def get_resnet(num_classes=1000, num_layers=50):
    """ImageNet ResNet. Input is NCHW 3x224x224."""
    units, bottleneck = _UNITS[num_layers]
    filters = [256, 512, 1024, 2048] if bottleneck else [64, 128, 256, 512]
    data = sym.Variable("data")
    body = _conv_bn(data, 64, (7, 7), (2, 2), (3, 3), "stem")
    body = sym.Pooling(body, pool_type="max", kernel=(3, 3), stride=(2, 2),
                       name="stem_pool")
    for si, (n, f) in enumerate(zip(units, filters), start=1):
        for ui in range(n):
            stride = (2, 2) if si > 1 and ui == 0 else (1, 1)
            body = residual_unit(body, f, stride, ui > 0,
                                 "stage%d_unit%d" % (si, ui + 1),
                                 bottleneck)
    pool = sym.Pooling(body, pool_type="avg", kernel=(1, 1), global_pool=True,
                       name="global_pool")
    flat = sym.Flatten(pool)
    fc = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc, name="softmax")


def get_resnet_cifar(num_classes=10, n=3, image_hw=28):
    """CIFAR 6n+2 ResNet (n=3 -> 20 layers); reference
    symbol_resnet-28-small.py trains on 28x28 crops."""
    data = sym.Variable("data")
    body = _conv_bn(data, 16, (3, 3), (1, 1), (1, 1), "stem")
    for si, f in enumerate([16, 32, 64], start=1):
        for ui in range(n):
            stride = (2, 2) if si > 1 and ui == 0 else (1, 1)
            body = residual_unit(body, f, stride, not (ui == 0 and si > 1),
                                 "stage%d_unit%d" % (si, ui + 1),
                                 bottleneck=False)
    final_hw = image_hw // 4
    pool = sym.Pooling(body, pool_type="avg", kernel=(final_hw, final_hw),
                       name="global_pool")
    flat = sym.Flatten(pool)
    fc = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc, name="softmax")
