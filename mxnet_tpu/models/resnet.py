"""ResNets (He et al. 2015).

* :func:`get_resnet_cifar` — the 6n+2 CIFAR net (reference
  ``symbol_resnet-28-small.py``: conv3x3-16 stem, three stages of n
  residual units at 16/32/64 filters, global-avg-pool, fc).
* :func:`get_resnet` — ImageNet ResNet-18/34/50/101/152. ResNet-50 is the
  BASELINE.json north-star benchmark model, so this is the framework's
  flagship: bench.py and __graft_entry__ build it through this function.

TPU notes: all convs are NCHW symbols lowered to ``lax.conv_general_dilated``
— XLA lays them out for the MXU and fuses the BN+ReLU chains into the conv
epilogues, which is exactly the fusion the reference needed cuDNN for.
"""
from .. import symbol as sym


def _conv_bn(data, num_filter, kernel, stride, pad, name, act=True,
             eps=2e-5, momentum=0.9):
    c = sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, no_bias=True,
                        name=name + "_conv")
    b = sym.BatchNorm(c, eps=eps, momentum=momentum, fix_gamma=False,
                      name=name + "_bn")
    if act:
        return sym.Activation(b, act_type="relu", name=name + "_relu")
    return b


def residual_unit(data, num_filter, stride, dim_match, name,
                  bottleneck=True):
    """Post-activation residual unit (v1). ``dim_match=False`` projects the
    shortcut with a strided 1x1 conv+BN."""
    if bottleneck:
        mid = num_filter // 4
        body = _conv_bn(data, mid, (1, 1), (1, 1), (0, 0), name + "_a")
        body = _conv_bn(body, mid, (3, 3), stride, (1, 1), name + "_b")
        body = _conv_bn(body, num_filter, (1, 1), (1, 1), (0, 0),
                        name + "_c", act=False)
    else:
        body = _conv_bn(data, num_filter, (3, 3), stride, (1, 1),
                        name + "_a")
        body = _conv_bn(body, num_filter, (3, 3), (1, 1), (1, 1),
                        name + "_b", act=False)
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv_bn(data, num_filter, (1, 1), stride, (0, 0),
                            name + "_sc", act=False)
    return sym.Activation(body + shortcut, act_type="relu",
                          name=name + "_out")


_UNITS = {
    18: ([2, 2, 2, 2], False),
    34: ([3, 4, 6, 3], False),
    50: ([3, 4, 6, 3], True),
    101: ([3, 4, 23, 3], True),
    152: ([3, 8, 36, 3], True),
}


def get_resnet(num_classes=1000, num_layers=50, stem="standard"):
    """ImageNet ResNet. Input is NCHW 3x224x224.

    ``stem="s2d"`` replaces the 7x7/2 stem convolution with the
    MLPerf-style space-to-depth form: SpaceToDepth(2) then a 4x4/1
    convolution on 12 channels (cropped back to the same spatial size)
    — EXACTLY the same function (see ``convert_stem_weight_s2d``). The
    stem weight shape changes to [64, 12, 4, 4]; convert standard
    checkpoints with ``convert_stem_weight_s2d``. Measured on the v5e:
    the IN-GRAPH transform is slightly SLOWER end-to-end (the full-res
    reshuffle costs more than the MXU-friendlier conv saves) — it
    exists as the drop-in-compatible form.

    ``stem="s2d_input"`` is the fast form: the network consumes data
    ALREADY dealt to (12, 112, 112) — do the transform once in the
    input pipeline (``space_to_depth_batch``), where it replaces the
    h2d transfer's layout anyway. Measured ~+2.5% end-to-end
    (doc/performance.md).
    """
    units, bottleneck = _UNITS[num_layers]
    filters = [256, 512, 1024, 2048] if bottleneck else [64, 128, 256, 512]
    data = sym.Variable("data")
    if stem in ("s2d", "s2d_input"):
        # "s2d": deal in-graph; "s2d_input": data arrives pre-dealt
        body = (sym.SpaceToDepth(data, block_size=2, name="stem_s2d")
                if stem == "s2d" else data)
        body = sym.Convolution(body, num_filter=64, kernel=(4, 4),
                               stride=(1, 1), pad=(2, 2), no_bias=True,
                               name="stem_conv")
        # pad 2 (symmetric) overshoots the exact left-2/right-1 halo by
        # one row/col; crop back so every output pixel matches the
        # standard stem bit-for-bit (Crop keeps offset (0,0))
        body = sym.Crop(body, offset=(0, 0), h_w=(112, 112), num_args=1,
                        name="stem_crop")
        body = sym.BatchNorm(body, eps=2e-5, momentum=0.9,
                             fix_gamma=False, name="stem_bn")
        body = sym.Activation(body, act_type="relu", name="stem_relu")
    elif stem == "standard":
        body = _conv_bn(data, 64, (7, 7), (2, 2), (3, 3), "stem")
    else:
        raise ValueError("get_resnet: stem must be 'standard', 's2d' "
                         "or 's2d_input'")
    body = sym.Pooling(body, pool_type="max", kernel=(3, 3), stride=(2, 2),
                       name="stem_pool")
    for si, (n, f) in enumerate(zip(units, filters), start=1):
        for ui in range(n):
            stride = (2, 2) if si > 1 and ui == 0 else (1, 1)
            body = residual_unit(body, f, stride, ui > 0,
                                 "stage%d_unit%d" % (si, ui + 1),
                                 bottleneck)
    pool = sym.Pooling(body, pool_type="avg", kernel=(1, 1), global_pool=True,
                       name="global_pool")
    flat = sym.Flatten(pool)
    fc = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc, name="softmax")


def get_resnet_cifar(num_classes=10, n=3, image_hw=28):
    """CIFAR 6n+2 ResNet (n=3 -> 20 layers); reference
    symbol_resnet-28-small.py trains on 28x28 crops."""
    data = sym.Variable("data")
    body = _conv_bn(data, 16, (3, 3), (1, 1), (1, 1), "stem")
    for si, f in enumerate([16, 32, 64], start=1):
        for ui in range(n):
            stride = (2, 2) if si > 1 and ui == 0 else (1, 1)
            body = residual_unit(body, f, stride, not (ui == 0 and si > 1),
                                 "stage%d_unit%d" % (si, ui + 1),
                                 bottleneck=False)
    final_hw = image_hw // 4
    pool = sym.Pooling(body, pool_type="avg", kernel=(final_hw, final_hw),
                       name="global_pool")
    flat = sym.Flatten(pool)
    fc = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc, name="softmax")


def convert_stem_weight_s2d(w):
    """EXACT reparameterization of a standard [O, C, 7, 7] stride-2 stem
    weight into the [O, C*4, 4, 4] stride-1 weight the ``stem="s2d"``
    graph uses: with input pixels dealt as z[c*4 + p*2 + q, i, j] =
    x[c, 2i+p, 2j+q], matching the original needs u = 2a + p - 1 (and
    likewise for columns), so kernel tap (u, v) lands at
    (a, b) = ((u+1)//2, (v+1)//2) with parities ((u+1)%2, (v+1)%2);
    the unreachable (a=0, parity=0) taps stay zero."""
    import numpy as np
    w = np.asarray(w)
    O, C, kh, kw = w.shape
    if (kh, kw) != (7, 7):
        raise ValueError("convert_stem_weight_s2d expects a 7x7 kernel")
    out = np.zeros((O, C * 4, 4, 4), w.dtype)
    for u in range(7):
        a, p = (u + 1) // 2, (u + 1) % 2
        for v in range(7):
            b, q = (v + 1) // 2, (v + 1) % 2
            for c in range(C):
                out[:, c * 4 + p * 2 + q, a, b] = w[:, c, u, v]
    return out


def space_to_depth_batch(x, block_size=2):
    """Host-side input transform for ``get_resnet(stem="s2d_input")``:
    [B, C, H, W] -> [B, C*bs*bs, H/bs, W/bs] with the same channel
    order as the SpaceToDepth op (c*bs*bs + p*bs + q)."""
    import numpy as np
    x = np.asarray(x)
    b, c, h, w = x.shape
    bs = block_size
    r = x.reshape(b, c, h // bs, bs, w // bs, bs)
    return np.ascontiguousarray(
        r.transpose(0, 1, 3, 5, 2, 4)).reshape(b, c * bs * bs,
                                               h // bs, w // bs)
