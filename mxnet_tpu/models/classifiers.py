"""Small MNIST-class models (reference ``example/image-classification/
train_mnist.py:15-54``: get_mlp / get_lenet)."""
from .. import symbol as sym


def get_mlp(num_classes=10, hidden=(128, 64)):
    """3-layer perceptron (train_mnist.py:15-26)."""
    net = sym.Variable("data")
    for i, h in enumerate(hidden):
        net = sym.FullyConnected(net, name="fc%d" % (i + 1), num_hidden=h)
        net = sym.Activation(net, name="relu%d" % (i + 1), act_type="relu")
    net = sym.FullyConnected(net, name="fc%d" % (len(hidden) + 1),
                             num_hidden=num_classes)
    return sym.SoftmaxOutput(net, name="softmax")


def get_lenet(num_classes=10):
    """LeNet-style conv net (train_mnist.py:28-54): two conv/tanh/pool
    stages then two fully-connected layers."""
    data = sym.Variable("data")
    c1 = sym.Convolution(data, name="conv1", kernel=(5, 5), num_filter=20)
    a1 = sym.Activation(c1, act_type="tanh")
    p1 = sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = sym.Convolution(p1, name="conv2", kernel=(5, 5), num_filter=50)
    a2 = sym.Activation(c2, act_type="tanh")
    p2 = sym.Pooling(a2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    fl = sym.Flatten(p2)
    f1 = sym.FullyConnected(fl, name="fc1", num_hidden=500)
    a3 = sym.Activation(f1, act_type="tanh")
    f2 = sym.FullyConnected(a3, name="fc2", num_hidden=num_classes)
    return sym.SoftmaxOutput(f2, name="softmax")
