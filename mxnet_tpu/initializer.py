"""Weight initializers.

Parity: ``/root/reference/python/mxnet/initializer.py`` — name-pattern
dispatch (``*_bias``→0, ``*_gamma``→1, ``*_beta``→0, ``*_moving_mean``→0,
``*_moving_var``→1, else weight init), plus Uniform/Normal/Orthogonal/
Xavier/MSRAPrelu/Load/Mixed.
"""
from __future__ import annotations

import re

import numpy as np

from .base import string_types
from .ndarray import NDArray, array
from . import random

__all__ = ["Initializer", "Uniform", "Normal", "Orthogonal", "Xavier",
           "MSRAPrelu", "Load", "Mixed"]


class Initializer:
    """Base: dispatch on parameter name (reference initializer.py:14)."""

    def __call__(self, name, arr):
        if not isinstance(name, string_types):
            raise TypeError("name must be string")
        if not isinstance(arr, NDArray):
            raise TypeError("arr must be NDArray")
        if name.endswith("upsampling"):
            self._init_bilinear(name, arr)
        elif name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("beta"):
            self._init_beta(name, arr)
        elif name.endswith("weight"):
            self._init_weight(name, arr)
        elif name.endswith("embed"):
            # learned embeddings (e.g. pos_embed) init like weights
            self._init_weight(name, arr)
        elif "_expert_w" in name:
            self._init_expert(name, arr)  # MoE expert kernels
        elif "_expert_b" in name:
            self._init_bias(name, arr)
        elif name.endswith("moving_mean"):
            self._init_zero(name, arr)
        elif name.endswith("moving_var"):
            self._init_one(name, arr)
        elif name.endswith("moving_avg"):
            self._init_zero(name, arr)
        else:
            self._init_default(name, arr)

    def _init_bilinear(self, _, arr):
        weight = np.zeros(np.prod(arr.shape), dtype="float32")
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError("Must override it")

    def _init_expert(self, name, arr):
        """MoE expert banks [X, out, in]: initialize each expert's 2-D
        kernel independently so fan-in/out (and orthogonality) are
        per-expert, not across the flattened bank."""
        import numpy as _np
        from . import ndarray as _nd
        if arr.ndim <= 2:
            self._init_weight(name, arr)
            return
        out = _np.empty(arr.shape, dtype=_np.float32)
        for x in range(arr.shape[0]):
            sl = _nd.empty(arr.shape[1:])
            self._init_weight(name, sl)
            out[x] = sl.asnumpy()
        arr[:] = out

    def _init_default(self, name, _):
        raise ValueError(
            "Unknown initialization pattern for %s" % name)


class Load:
    """Initialize by loading from a param dict; fall back to ``default_init``
    (reference initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray import load
            param = load(param)
        self.param = {}
        for name, arr in param.items():
            if name.startswith("arg:") or name.startswith("aux:"):
                self.param[name[4:]] = arr
            else:
                self.param[name] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            assert arr.shape == self.param[name].shape, \
                "Parameter %s cannot be initialized from loading. " % name + \
                "Shape mismatch, target %s vs loaded %s" % \
                (str(arr.shape), str(self.param[name].shape))
            self.param[name].copyto(arr)
        else:
            assert self.default_init is not None, \
                "Cannot Initialize %s. Not found in loaded param " % name + \
                "and no default Initializer is provided."
            self.default_init(name, arr)


class Mixed:
    """Regex-pattern list → initializer list (reference initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        assert len(patterns) == len(initializers)
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError("Parameter name %s did not match any pattern."
                         % name)


class Uniform(Initializer):
    """Uniform draw on [-scale, scale]."""

    def __init__(self, scale=0.07):
        self.scale = float(scale)

    def _init_weight(self, _, arr):
        random.uniform(-self.scale, self.scale, out=arr)


class Normal(Initializer):
    """Zero-mean gaussian draw with standard deviation ``sigma``."""

    def __init__(self, sigma=0.01):
        self.sigma = float(sigma)

    def _init_weight(self, _, arr):
        random.normal(0, self.sigma, out=arr)


class Orthogonal(Initializer):
    """Orthogonal init (reference initializer.py; Saxe et al. 2013)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        res = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * res).reshape(arr.shape)


class Xavier(Initializer):
    """Xavier/Glorot init (reference initializer.py Xavier): draw from a
    distribution scaled by ``sqrt(magnitude / factor)`` where ``factor``
    is a fan statistic of the weight. Convolution kernels [O, I, *K]
    count the receptive field into both fans."""

    _FACTOR = {"avg": lambda fi, fo: (fi + fo) / 2.0,
               "in": lambda fi, fo: fi,
               "out": lambda fi, fo: fo}
    _DRAW = {"uniform": lambda s, arr: random.uniform(-s, s, out=arr),
             "gaussian": lambda s, arr: random.normal(0, s, out=arr)}

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        if factor_type not in self._FACTOR:
            raise ValueError("Incorrect factor type")
        if rnd_type not in self._DRAW:
            raise ValueError("Unknown random type")
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, _, arr):
        receptive = float(np.prod(arr.shape[2:])) if arr.ndim > 2 else 1.0
        fans = arr.shape[1] * receptive, arr.shape[0] * receptive
        scale = np.sqrt(self.magnitude / self._FACTOR[self.factor_type](*fans))
        self._DRAW[self.rnd_type](scale, arr)


class MSRAPrelu(Xavier):
    """MSRA init for PReLU nets (reference initializer.py MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
