"""Tracing / profiling.

The reference has no dedicated profiler — its observability is the
Monitor callback, `Speedometer`, plan dumps and `MXNET_ENGINE_INFO` op
logs (SURVEY §5). On TPU the right tool is the XLA profiler: this module
wraps ``jax.profiler`` with a stable mxnet-style surface so traces can be
captured around any training region and opened in TensorBoard/Perfetto.

Usage::

    mx.profiler.start("/tmp/traces")     # or profiler_set_config + start
    ... training steps ...
    mx.profiler.stop()

    with mx.profiler.scope("epoch-3"):   # named sub-regions in the trace
        train_epoch()
"""
from __future__ import annotations

import contextlib

import jax

_state = {"dir": None, "running": False}


def profiler_set_config(output_dir: str):
    """Configure the trace output directory before :func:`start`."""
    _state["dir"] = output_dir


def start(output_dir: str | None = None):
    """Begin capturing a device+host trace."""
    if output_dir is not None:
        _state["dir"] = output_dir
    if _state["dir"] is None:
        raise ValueError("profiler: no output dir configured")
    jax.profiler.start_trace(_state["dir"])
    _state["running"] = True


def stop():
    """End the capture and flush the trace to the output dir."""
    if _state["running"]:
        jax.profiler.stop_trace()
        _state["running"] = False


@contextlib.contextmanager
def scope(name: str):
    """Annotate a named region; nests inside an active trace."""
    with jax.profiler.TraceAnnotation(name):
        yield


def device_memory_profile() -> bytes:
    """Snapshot of current device memory (pprof format)."""
    return jax.profiler.device_memory_profile()
