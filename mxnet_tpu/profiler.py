"""Tracing / profiling.

The reference has no dedicated profiler — its observability is the
Monitor callback, `Speedometer`, plan dumps and `MXNET_ENGINE_INFO` op
logs (SURVEY §5). On TPU the right tool is the XLA profiler: this module
wraps ``jax.profiler`` with a stable mxnet-style surface so traces can be
captured around any training region and opened in TensorBoard/Perfetto.

Usage::

    mx.profiler.start("/tmp/traces")     # or profiler_set_config + start
    ... training steps ...
    mx.profiler.stop()

    with mx.profiler.scope("epoch-3"):   # named sub-regions in the trace
        train_epoch()
"""
from __future__ import annotations

import contextlib
import weakref as _weakref

import jax
import numpy as _np

from . import telemetry

_state = {"dir": None, "running": False}


def profiler_set_config(output_dir: str):
    """Configure the trace output directory before :func:`start`."""
    _state["dir"] = output_dir


def start(output_dir: str | None = None):
    """Begin capturing a device+host trace."""
    if output_dir is not None:
        _state["dir"] = output_dir
    if _state["dir"] is None:
        raise ValueError("profiler: no output dir configured")
    jax.profiler.start_trace(_state["dir"])
    _state["running"] = True


def stop():
    """End the capture and flush the trace to the output dir."""
    if _state["running"]:
        jax.profiler.stop_trace()
        _state["running"] = False


@contextlib.contextmanager
def scope(name: str):
    """Annotate a named region: an XLA ``TraceAnnotation`` (shows up in
    the ``mx.profiler.start``/TensorBoard device trace) AND an
    ``mx.telemetry`` span (shows up in the ``MXNET_TRACE_DIR``
    host-side Chrome trace) — one ``with`` statement marks the region
    in both captures, so device and host timelines can be lined up in
    Perfetto by name. See doc/observability.md."""
    with jax.profiler.TraceAnnotation(name):
        with telemetry.span(name, cat="profiler.scope"):
            yield


def device_memory_profile() -> bytes:
    """Snapshot of current device memory (pprof format)."""
    return jax.profiler.device_memory_profile()


# ---------------------------------------------------------------------------
# step statistics (Speedometer-adjacent, but library-level: the reference
# logs samples/sec from a callback; this accumulates step wall-times so
# perf regressions are visible without TensorBoard — important on relay
# environments where trace capture is awkward)

import time as _time

_steps = {"times": []}


@contextlib.contextmanager
def record_step():
    """Time one training step:  ``with mx.profiler.record_step(): step()``.
    Includes device wait only if the caller blocks (as FeedForward's
    metric update does); pair with get_step_stats()."""
    tic = _time.perf_counter()
    try:
        yield
    finally:
        _steps["times"].append(_time.perf_counter() - tic)


def reset_step_stats():
    _steps["times"] = []


def get_step_stats():
    """dict(count, mean_ms, p50_ms, p99_ms, total_s) over recorded steps."""
    ts = sorted(_steps["times"])
    if not ts:
        return {"count": 0, "mean_ms": 0.0, "p50_ms": 0.0, "p99_ms": 0.0,
                "total_s": 0.0}
    n = len(ts)
    return {
        "count": n,
        "mean_ms": 1e3 * sum(ts) / n,
        "p50_ms": 1e3 * ts[n // 2],
        "p99_ms": 1e3 * ts[min(n - 1, (99 * n) // 100)],
        "total_s": sum(ts),
    }


# ---------------------------------------------------------------------------
# honest throughput measurement — the two-chain methodology from
# doc/performance.md as a library API. On relay/tunnel TPU environments
# `block_until_ready` can return before execution finishes, so naive
# timing reports impossible numbers; this utility times two DEPENDENT
# chain lengths that each end in a real value fetch and differences
# them, cancelling the constant dispatch/flush overhead. This is the
# LIBRARY form of the methodology doc/performance.md describes;
# bench.py (the driver) keeps its own driver-local variant with
# glitch-retry heuristics tuned for unattended runs.

def benchmark_chain(step_fn, state, *, steps=15, reps=3,
                    fetch=None):
    """Seconds per call of ``state = step_fn(state)``.

    ``step_fn`` MUST thread its output back as its input (a donated
    train step, ``y = f(y)``, ...) — that data dependence is what makes
    the timing honest. ``fetch(state)`` forces completion (default:
    ``np.asarray`` of the first leaf's first element). Returns
    ``(seconds_per_step, spread)`` where spread is the relative
    max-min range across ``reps`` measurements — distrust results
    with spread > 0.1.
    """
    import numpy as _np

    if fetch is None:
        def fetch(s):
            leaf = jax.tree_util.tree_leaves(s)[0]
            _np.asarray(leaf).ravel()[:1]

    def chain(n, s):
        tic = _time.perf_counter()
        for _ in range(n):
            s = step_fn(s)
        fetch(s)
        return _time.perf_counter() - tic, s

    _, state = chain(3, state)  # warmup/compile
    diffs = []
    for _ in range(reps):
        t1, state = chain(steps, state)
        t2, state = chain(2 * steps, state)
        if t2 - t1 > 0:
            diffs.append((t2 - t1) / steps)
    if not diffs:
        raise RuntimeError(
            "benchmark_chain: no positive chain difference — the relay "
            "glitched every rep; rerun, or raise `steps` so compute "
            "dominates the flush-cost variance")
    dt = float(sorted(diffs)[len(diffs) // 2])
    spread = (max(diffs) - min(diffs)) / dt if len(diffs) > 1 else 0.0
    return dt, spread


# ---------------------------------------------------------------------------
# compiled-program analysis (the reference's example/memcost tool reports
# the memory planner's totals; XLA's equivalents are memory_analysis and
# cost_analysis on the compiled executable)

def compiled_stats(compiled):
    """FLOPs/bytes/memory for a compiled jax function (the object
    returned by ``jax.jit(f).lower(...).compile()``) or for an Executor
    (uses its infer program). Returns a dict with whatever the backend
    reports: flops, bytes_accessed, argument/output/temp sizes."""
    if hasattr(compiled, "_compiled_infer"):  # Executor duck-type
        compiled = compiled._compiled_infer()  # cached; no recompile
    out = {}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        for k in ("flops", "bytes accessed"):
            if k in cost:
                out[k.replace(" ", "_")] = float(cost[k])
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        for k in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(ma, k, None)
            if v is not None:
                out[k] = int(v)
    except Exception:
        pass
    return out


# ---------------------------------------------------------------------------
# XLA program introspection registry (doc/observability.md "Program and
# device introspection"): the hot-path jit sites — the serving engine's
# three program families, the fused trainer step — REGISTER their
# jitted callable + argument avals here at first dispatch, and
# `collect_program_stats` turns registrations into `program.*` gauges
# on demand. Two-phase on purpose:
#
# * registration is nearly free: one tree_map to ShapeDtypeStructs
#   (nothing device-resident is retained — donated buffers must not be
#   pinned by an introspection registry);
# * collection reads `Lowered.cost_analysis()` through jax's lowering
#   cache — the avals match the dispatch that already traced, so this
#   re-traces nothing, compiles nothing, and never touches the device.
#   `compile=True` additionally AOT-compiles for the exact post-
#   optimization `memory_analysis()` (one extra backend compile per
#   program, cached by jax thereafter) — bench/tool territory, never
#   the scrape path.
#
# Everything is best-effort on jax 0.4.37: an analysis a backend
# doesn't report degrades to an absent gauge, never an error.

_programs = {}        # name -> (jitted_fn, aval_args)
_collected = {}       # name -> depth collected ("cost" | "memory")

# thread-local "a collection lower() is running" flag: when the
# lowering cache HITS (the normal case — collection uses the avals the
# dispatch traced with) nothing re-runs; if it ever MISSES (e.g.
# committed-array avals on a real chip), the re-trace replays
# trace-time side effects — the serving engine's compile-count log
# checks this flag so an introspection re-trace can never corrupt the
# pinned compile contract. Thread-local so a scrape-thread collection
# never masks a real compile on the dispatch thread.
import threading as _threading

_collecting = _threading.local()


def collecting():
    """True on the thread currently lowering for introspection."""
    return getattr(_collecting, "active", False)


def _aval(x):
    """Shape/dtype skeleton of one argument leaf. Arrays (jax, numpy,
    numpy scalars) become ShapeDtypeStructs; python scalars pass
    through unchanged — their weak type is part of the lowering cache
    key, and substituting a typed aval would force a re-trace."""
    if isinstance(x, jax.Array) or isinstance(x, (_np.ndarray,
                                                  _np.generic)):
        return jax.ShapeDtypeStruct(_np.shape(x), x.dtype)
    return x


def register_program(name, fn, args, eager=True):
    """Register a jitted program for introspection: ``fn`` is the
    ``jax.jit`` callable, ``args`` the positional arguments of a real
    dispatch (converted to avals immediately; safe to call with
    donated buffers). Re-registering a name (a recompile) clears its
    collected stats so the next collection refreshes the gauges.

    The callable is held by WEAK reference: a jit wrapper's closure
    reaches its owner (the serving engine's traced step appends to
    ``self._compile_log`` — so ``fn`` transitively pins the engine,
    its slot-paged KV cache and the decoder weights). A strong
    registry entry would keep a dropped engine's device memory alive
    forever and defeat the ``serving/engine._ENGINES`` WeakSet;
    dead registrations are pruned at the next collection instead.

    ``eager=True`` (the default) collects the COST gauges right here,
    through the lowering the dispatch just populated (a cache hit:
    ~ms, no re-trace) — so the gauges survive the owner being dropped
    (FeedForward.fit discards its trainer after fitting; serving
    engines churn through restore()). Worst case on a cache miss is
    one abstract re-trace at the registration site. ``eager=False``
    defers to the next ``collect_program_stats`` — only correct for
    owners that outlive the scrape."""
    try:
        avals = tuple(jax.tree_util.tree_map(_aval, a) for a in args)
        ref = _weakref.ref(fn)
    except Exception:
        return                      # introspection must never raise
    _programs[name] = (ref, avals)
    _collected.pop(name, None)
    if eager:
        try:
            _collect_one(name, fn, avals, compile=False)
        except Exception:
            pass


def _collect_one(name, fn, avals, compile):
    """Lower + analyze one program into its gauges; returns the stats
    dict (empty when the backend reports nothing)."""
    stats = {}
    _collecting.active = True
    try:
        low = fn.lower(*avals)
    finally:
        _collecting.active = False
    try:
        cost = low.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0] if cost else {}
        for k in ("flops", "bytes accessed", "transcendentals"):
            if k in cost:
                stats[k.replace(" ", "_")] = float(cost[k])
    except Exception:
        pass
    if compile:
        try:
            ma = low.compile().memory_analysis()
            for k in ("argument_size_in_bytes",
                      "output_size_in_bytes", "temp_size_in_bytes",
                      "generated_code_size_in_bytes"):
                v = getattr(ma, k, None)
                if v is not None:
                    stats[k.replace("_size_in_bytes", "_bytes")] \
                        = int(v)
        except Exception:
            pass
    for k, v in stats.items():
        telemetry.gauge("program.%s.%s" % (name, k)).set(v)
    _collected[name] = "memory" if compile else "cost"
    return stats


def collect_program_stats(compile=False):
    """Materialize `program.<name>.*` gauges for every registered
    program; returns ``{name: {stat: value}}``. Cheap by default (see
    the registry note above); ``compile=True`` adds the compiled
    memory analysis. Already-collected programs are skipped until
    re-registered (or a deeper collection is requested)."""
    out = {}
    want = "memory" if compile else "cost"
    for name, (ref, avals) in list(_programs.items()):
        fn = ref()
        if fn is None:              # owner dropped: prune, don't pin
            _programs.pop(name, None)
            _collected.pop(name, None)
            continue
        if _collected.get(name) in (want, "memory"):
            continue
        try:
            stats = _collect_one(name, fn, avals, compile)
        except Exception:
            continue                # e.g. avals no longer lowerable
        if stats:
            out[name] = stats
    return out


def registered_programs():
    """Names currently registered for introspection."""
    return sorted(_programs)


# device-memory watermarks: the live-array census works on every
# backend (it is jax's own bookkeeping, no device op); allocator
# stats (bytes_in_use / peak / limit) exist only where the backend
# reports them (TPU/GPU) and degrade to absent gauges elsewhere
_dev_peak = {"live": 0.0}


def device_memory():
    """Best-effort device-memory occupancy, refreshed into `device.*`
    gauges and returned as a dict. Host-side only: a census of live
    ``jax.Array`` bytes (every backend) plus allocator stats where the
    backend exposes ``Device.memory_stats()`` (absent on CPU). The
    live-bytes watermark persists across calls, so a snapshot diff
    across a workload shows its HBM high-water mark."""
    out = {}
    try:
        live_bytes = 0
        live_count = 0
        for a in jax.live_arrays():
            try:
                if not a.is_deleted():
                    live_bytes += a.nbytes
                    live_count += 1
            except Exception:
                continue
        _dev_peak["live"] = max(_dev_peak["live"], float(live_bytes))
        telemetry.gauge("device.live_array_bytes").set(live_bytes)
        telemetry.gauge("device.live_arrays").set(live_count)
        telemetry.gauge("device.live_array_peak_bytes").set(
            _dev_peak["live"])
        out.update(live_array_bytes=live_bytes,
                   live_arrays=live_count,
                   live_array_peak_bytes=_dev_peak["live"])
    except Exception:
        pass
    try:
        in_use = peak = limit = 0
        have = False
        for d in jax.devices():
            ms = getattr(d, "memory_stats", None)
            ms = ms() if callable(ms) else None
            if not ms:
                continue
            have = True
            in_use += ms.get("bytes_in_use", 0)
            peak += ms.get("peak_bytes_in_use", 0)
            limit += ms.get("bytes_limit", 0)
        if have:
            telemetry.gauge("device.bytes_in_use").set(in_use)
            telemetry.gauge("device.peak_bytes_in_use").set(peak)
            telemetry.gauge("device.bytes_limit").set(limit)
            out.update(bytes_in_use=in_use, peak_bytes_in_use=peak,
                       bytes_limit=limit)
    except Exception:
        pass
    return out
