"""Tracing / profiling.

The reference has no dedicated profiler — its observability is the
Monitor callback, `Speedometer`, plan dumps and `MXNET_ENGINE_INFO` op
logs (SURVEY §5). On TPU the right tool is the XLA profiler: this module
wraps ``jax.profiler`` with a stable mxnet-style surface so traces can be
captured around any training region and opened in TensorBoard/Perfetto.

Usage::

    mx.profiler.start("/tmp/traces")     # or profiler_set_config + start
    ... training steps ...
    mx.profiler.stop()

    with mx.profiler.scope("epoch-3"):   # named sub-regions in the trace
        train_epoch()
"""
from __future__ import annotations

import contextlib

import jax

from . import telemetry

_state = {"dir": None, "running": False}


def profiler_set_config(output_dir: str):
    """Configure the trace output directory before :func:`start`."""
    _state["dir"] = output_dir


def start(output_dir: str | None = None):
    """Begin capturing a device+host trace."""
    if output_dir is not None:
        _state["dir"] = output_dir
    if _state["dir"] is None:
        raise ValueError("profiler: no output dir configured")
    jax.profiler.start_trace(_state["dir"])
    _state["running"] = True


def stop():
    """End the capture and flush the trace to the output dir."""
    if _state["running"]:
        jax.profiler.stop_trace()
        _state["running"] = False


@contextlib.contextmanager
def scope(name: str):
    """Annotate a named region: an XLA ``TraceAnnotation`` (shows up in
    the ``mx.profiler.start``/TensorBoard device trace) AND an
    ``mx.telemetry`` span (shows up in the ``MXNET_TRACE_DIR``
    host-side Chrome trace) — one ``with`` statement marks the region
    in both captures, so device and host timelines can be lined up in
    Perfetto by name. See doc/observability.md."""
    with jax.profiler.TraceAnnotation(name):
        with telemetry.span(name, cat="profiler.scope"):
            yield


def device_memory_profile() -> bytes:
    """Snapshot of current device memory (pprof format)."""
    return jax.profiler.device_memory_profile()


# ---------------------------------------------------------------------------
# step statistics (Speedometer-adjacent, but library-level: the reference
# logs samples/sec from a callback; this accumulates step wall-times so
# perf regressions are visible without TensorBoard — important on relay
# environments where trace capture is awkward)

import time as _time

_steps = {"times": []}


@contextlib.contextmanager
def record_step():
    """Time one training step:  ``with mx.profiler.record_step(): step()``.
    Includes device wait only if the caller blocks (as FeedForward's
    metric update does); pair with get_step_stats()."""
    tic = _time.perf_counter()
    try:
        yield
    finally:
        _steps["times"].append(_time.perf_counter() - tic)


def reset_step_stats():
    _steps["times"] = []


def get_step_stats():
    """dict(count, mean_ms, p50_ms, p99_ms, total_s) over recorded steps."""
    ts = sorted(_steps["times"])
    if not ts:
        return {"count": 0, "mean_ms": 0.0, "p50_ms": 0.0, "p99_ms": 0.0,
                "total_s": 0.0}
    n = len(ts)
    return {
        "count": n,
        "mean_ms": 1e3 * sum(ts) / n,
        "p50_ms": 1e3 * ts[n // 2],
        "p99_ms": 1e3 * ts[min(n - 1, (99 * n) // 100)],
        "total_s": sum(ts),
    }


# ---------------------------------------------------------------------------
# honest throughput measurement — the two-chain methodology from
# doc/performance.md as a library API. On relay/tunnel TPU environments
# `block_until_ready` can return before execution finishes, so naive
# timing reports impossible numbers; this utility times two DEPENDENT
# chain lengths that each end in a real value fetch and differences
# them, cancelling the constant dispatch/flush overhead. This is the
# LIBRARY form of the methodology doc/performance.md describes;
# bench.py (the driver) keeps its own driver-local variant with
# glitch-retry heuristics tuned for unattended runs.

def benchmark_chain(step_fn, state, *, steps=15, reps=3,
                    fetch=None):
    """Seconds per call of ``state = step_fn(state)``.

    ``step_fn`` MUST thread its output back as its input (a donated
    train step, ``y = f(y)``, ...) — that data dependence is what makes
    the timing honest. ``fetch(state)`` forces completion (default:
    ``np.asarray`` of the first leaf's first element). Returns
    ``(seconds_per_step, spread)`` where spread is the relative
    max-min range across ``reps`` measurements — distrust results
    with spread > 0.1.
    """
    import numpy as _np

    if fetch is None:
        def fetch(s):
            leaf = jax.tree_util.tree_leaves(s)[0]
            _np.asarray(leaf).ravel()[:1]

    def chain(n, s):
        tic = _time.perf_counter()
        for _ in range(n):
            s = step_fn(s)
        fetch(s)
        return _time.perf_counter() - tic, s

    _, state = chain(3, state)  # warmup/compile
    diffs = []
    for _ in range(reps):
        t1, state = chain(steps, state)
        t2, state = chain(2 * steps, state)
        if t2 - t1 > 0:
            diffs.append((t2 - t1) / steps)
    if not diffs:
        raise RuntimeError(
            "benchmark_chain: no positive chain difference — the relay "
            "glitched every rep; rerun, or raise `steps` so compute "
            "dominates the flush-cost variance")
    dt = float(sorted(diffs)[len(diffs) // 2])
    spread = (max(diffs) - min(diffs)) / dt if len(diffs) > 1 else 0.0
    return dt, spread


# ---------------------------------------------------------------------------
# compiled-program analysis (the reference's example/memcost tool reports
# the memory planner's totals; XLA's equivalents are memory_analysis and
# cost_analysis on the compiled executable)

def compiled_stats(compiled):
    """FLOPs/bytes/memory for a compiled jax function (the object
    returned by ``jax.jit(f).lower(...).compile()``) or for an Executor
    (uses its infer program). Returns a dict with whatever the backend
    reports: flops, bytes_accessed, argument/output/temp sizes."""
    if hasattr(compiled, "_compiled_infer"):  # Executor duck-type
        compiled = compiled._compiled_infer()  # cached; no recompile
    out = {}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        for k in ("flops", "bytes accessed"):
            if k in cost:
                out[k.replace(" ", "_")] = float(cost[k])
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        for k in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(ma, k, None)
            if v is not None:
                out[k] = int(v)
    except Exception:
        pass
    return out
