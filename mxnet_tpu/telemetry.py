"""Process-wide runtime telemetry: counters, gauges, histograms, spans.

The reference's only observability was the Monitor callback,
``Speedometer`` and ``MXNET_ENGINE_INFO`` op logs (SURVEY §5) — every
deeper question ("is the step starved on input or on the device?",
"how many kvstore retries did that epoch pay?") needed printf work.
This module is the shared instrumentation layer behind the rebuild's
four hot paths (fused trainer, IO pipeline, dist kvstore, serving
engine): a single named-metric registry, cheap enough to stay on by
default, plus Chrome ``trace_event`` spans that open in
Perfetto / chrome://tracing right next to ``mx.profiler``'s XLA traces.

Design constraints (and why the hot paths can afford this):

* **host-side only** — ``time.perf_counter`` and python ints; nothing
  here is ever traced into a compiled program and nothing forces a
  device sync. ``bench.py``'s overhead arm pins the fused-step cost
  of leaving telemetry on at < 2%.
* **pre-resolved handles** — instrumentation sites call
  ``counter(name)`` once at import and keep the object; the per-event
  cost is one enabled-flag check + one small-lock add.
* **no cross-process state** — pool workers (forked decode workers,
  kvstore servers in other processes) measure locally and ship plain
  floats back on messages they already send; only the consumer process
  feeds the registry.

Metric names are dotted (``subsystem.metric``); :func:`snapshot` nests
them into a dict tree and :func:`to_prometheus` renders the standard
text exposition. doc/observability.md has the per-subsystem catalog.

Knobs: ``MXNET_TELEMETRY=0`` disables collection entirely;
``MXNET_TRACE_DIR=<dir>`` arms span capture at import (flushed at
process exit, or explicitly via :func:`stop_trace`);
``MXNET_TELEMETRY_LOG_INTERVAL=<seconds>`` starts a background
reporter that logs a compact summary on that cadence.
"""
from __future__ import annotations

import atexit
import bisect
import collections
import contextlib
import json
import logging
import os
import re
import threading
import time

from .base import MXNetError

__all__ = ["counter", "gauge", "histogram", "snapshot", "to_prometheus",
           "span", "mark", "trace_complete", "start_trace", "stop_trace",
           "tracing", "tracing_paused", "enable", "enabled", "reset",
           "start_reporter", "stop_reporter", "serve", "stop_server",
           "Counter", "Gauge", "Histogram", "SloWindow"]

# default histogram buckets: wall-time milliseconds, µs-to-minutes —
# wide because the same shape serves sub-ms decode rounds and multi-s
# checkpoint writes; pass buckets= at first creation to specialize
DEFAULT_BUCKETS_MS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                      25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0,
                      5000.0, 10000.0, 60000.0)

_MAX_TRACE_EVENTS = 200_000  # bound the buffer; overflow is COUNTED


class _State:
    def __init__(self):
        self.enabled = os.environ.get("MXNET_TELEMETRY", "1") != "0"
        self.metrics = {}          # name -> metric object
        self.lock = threading.Lock()   # registry structure only
        # tracing
        self.trace_active = False
        self.trace_events = []
        self.trace_dropped = 0
        self.trace_lock = threading.Lock()
        self.trace_path = None
        self.trace_epoch = 0.0     # perf_counter origin of ts=0
        # reporter
        self.reporter = None
        self.reporter_stop = None


_state = _State()


# ---------------------------------------------------------------------------
# metric types

class Counter:
    """Monotonic event/byte counter. ``inc`` is thread-safe (CPython
    ``+=`` is a read-modify-write and CAN lose increments across
    threads; the per-metric lock is ~100 ns, cheap at host-path
    rates)."""

    __slots__ = ("name", "_v", "_lock")
    kind = "counter"

    def __init__(self, name):
        self.name = name
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        if not _state.enabled:
            return
        with self._lock:
            self._v += n

    @property
    def value(self):
        return self._v

    def _reset(self):
        with self._lock:
            self._v = 0

    def _snap(self):
        return self._v


class Gauge:
    """Last-write-wins instantaneous value (queue depth, occupancy,
    samples/sec)."""

    __slots__ = ("name", "_v")
    kind = "gauge"

    def __init__(self, name):
        self.name = name
        self._v = 0.0

    def set(self, v):
        if not _state.enabled:
            return
        self._v = float(v)   # single store: atomic under the GIL

    @property
    def value(self):
        return self._v

    def _reset(self):
        self._v = 0.0

    def _snap(self):
        return self._v


class Histogram:
    """Fixed-bucket histogram (Prometheus-style cumulative ``le``
    buckets) with count/sum/min/max. Percentiles are bucket-resolution
    approximations (the bucket's upper bound), which is what fixed
    buckets can honestly give without storing samples."""

    __slots__ = ("name", "buckets", "_counts", "_count", "_sum",
                 "_min", "_max", "_lock")
    kind = "histogram"

    def __init__(self, name, buckets=None):
        self.name = name
        self.buckets = tuple(float(b) for b in
                             (buckets or DEFAULT_BUCKETS_MS))
        if list(self.buckets) != sorted(set(self.buckets)):
            raise MXNetError("histogram %r: buckets must be strictly "
                             "ascending" % name)
        self._counts = [0] * (len(self.buckets) + 1)  # +1: +inf
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._lock = threading.Lock()

    def observe(self, v):
        if not _state.enabled:
            return
        v = float(v)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def percentile(self, q):
        """Upper bound of the bucket containing quantile ``q`` in
        [0, 1] (``nan`` when empty — a percentile of nothing is not a
        number, and a silent None used to poison arithmetic at the
        caller; max for the +inf bucket)."""
        with self._lock:
            total = self._count
            if total == 0:
                return float("nan")
            need = q * total
            acc = 0
            for i, c in enumerate(self._counts):
                acc += c
                if acc >= need:
                    if i < len(self.buckets):
                        return self.buckets[i]
                    return self._max
            return self._max

    def count_le(self, v):
        """Observations ``<=`` the smallest bucket bound ``>= v`` —
        the cumulative count a Prometheus ``le`` bucket would report.
        Exact when ``v`` IS a bucket bound; otherwise the threshold is
        quantized UP to the next bound (fixed buckets cannot resolve
        between bounds). ``v`` past the last bound counts everything.
        This is the attainment primitive :class:`SloWindow` reads."""
        i = bisect.bisect_left(self.buckets, float(v))
        with self._lock:
            if i >= len(self.buckets):
                return self._count
            return sum(self._counts[:i + 1])

    def _reset(self):
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None

    def _snap(self):
        with self._lock:
            if self._count == 0:
                return {"count": 0}
            snap = {
                "count": self._count,
                "sum": round(self._sum, 6),
                "mean": round(self._sum / self._count, 6),
                "min": round(self._min, 6),
                "max": round(self._max, 6),
                "buckets": {("%g" % b): c for b, c in
                            zip(self.buckets, self._counts)
                            if c},
            }
            if self._counts[-1]:
                snap["buckets"]["+Inf"] = self._counts[-1]
        snap["p50"] = self.percentile(0.50)
        snap["p99"] = self.percentile(0.99)
        return snap


# ---------------------------------------------------------------------------
# registry

def _get(name, cls, **kw):
    with _state.lock:
        m = _state.metrics.get(name)
        if m is None:
            m = cls(name, **kw) if kw else cls(name)
            _state.metrics[name] = m
        elif not isinstance(m, cls):
            raise MXNetError("telemetry metric %r already registered as "
                             "%s" % (name, m.kind))
        return m


def counter(name):
    """Get-or-create the named :class:`Counter`."""
    return _get(name, Counter)


def gauge(name):
    """Get-or-create the named :class:`Gauge`."""
    return _get(name, Gauge)


def histogram(name, buckets=None):
    """Get-or-create the named :class:`Histogram` (``buckets`` applies
    only on first creation)."""
    if buckets is None:
        return _get(name, Histogram)
    return _get(name, Histogram, buckets=buckets)


def enable(flag=True):
    """Globally enable/disable collection (``MXNET_TELEMETRY=0`` sets
    the import-time default). Disabled metrics keep their accumulated
    values; spans become no-ops."""
    _state.enabled = bool(flag)


def enabled():
    return _state.enabled


def reset():
    """Zero every registered metric and drop buffered trace events
    (registered objects stay valid — instrumentation sites hold
    references). Test/benchmark hygiene."""
    with _state.lock:
        metrics = list(_state.metrics.values())
    for m in metrics:
        m._reset()
    with _state.trace_lock:
        _state.trace_events = []
        _state.trace_dropped = 0


def snapshot(prefix=None):
    """Nested dict of every metric, keyed by the dotted name's
    segments: ``serving.ttft_ms`` lands at
    ``snap["serving"]["ttft_ms"]``. Counters/gauges are scalars,
    histograms small dicts (count/sum/mean/min/max/p50/p99/buckets).
    ``prefix`` restricts to names starting with it (e.g.
    ``"serving."`` — what ``/snapshot?prefix=serving.`` serves a
    fleet scraper that only wants the serving subtree)."""
    with _state.lock:
        items = sorted(_state.metrics.items())
    if prefix:
        items = [(n, m) for n, m in items if n.startswith(prefix)]
    names = {name for name, _ in items}
    out = {}
    for name, m in items:
        parts = name.split(".")
        d = out
        ok = True
        for i, p in enumerate(parts[:-1]):
            # an intermediate node that IS a registered metric must not
            # be descended into — a histogram's snapshot is a dict, and
            # "x.y.z" would silently merge into histogram "x.y"'s entry
            if ".".join(parts[:i + 1]) in names:
                ok = False
                break
            nxt = d.setdefault(p, {})
            if not isinstance(nxt, dict):
                ok = False
                break
            d = nxt
        if ok and parts[-1] not in d:
            d[parts[-1]] = m._snap()
        else:  # name collides with a subtree: fall back to the flat key
            out[name] = m._snap()
    return out


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def to_prometheus(prefix=None):
    """Prometheus text exposition of the registry (the shape a
    ``/metrics`` endpoint would serve). Dots become underscores;
    counters gain the conventional ``_total`` suffix; histograms emit
    cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``.
    ``prefix`` filters by DOTTED name prefix (pre-mangling:
    ``prefix="serving."`` keeps every ``mxnet_serving_*`` family) —
    the ``/metrics?prefix=`` subtree scrape."""
    lines = []
    with _state.lock:
        items = sorted(_state.metrics.items())
    if prefix:
        items = [(n, m) for n, m in items if n.startswith(prefix)]
    for name, m in items:
        base = "mxnet_" + _PROM_BAD.sub("_", name)
        if m.kind == "counter":
            lines.append("# TYPE %s_total counter" % base)
            lines.append("%s_total %d" % (base, m.value))
        elif m.kind == "gauge":
            lines.append("# TYPE %s gauge" % base)
            lines.append("%s %.17g" % (base, m.value))
        else:
            lines.append("# TYPE %s histogram" % base)
            acc = 0
            with m._lock:
                counts = list(m._counts)
                total, tsum = m._count, m._sum
                vmin, vmax = m._min, m._max
            for b, c in zip(m.buckets, counts):
                acc += c
                lines.append('%s_bucket{le="%g"} %d' % (base, b, acc))
            lines.append('%s_bucket{le="+Inf"} %d' % (base, total))
            lines.append("%s_sum %.17g" % (base, tsum))
            lines.append("%s_count %d" % (base, total))
            if total:
                # exact streaming extrema next to the bucket-approx
                # quantiles: scrapers can see how far a tail reading
                # may sit from the bucket bound that reported it
                lines.append("# TYPE %s_min gauge" % base)
                lines.append("%s_min %.17g" % (base, vmin))
                lines.append("# TYPE %s_max gauge" % base)
                lines.append("%s_max %.17g" % (base, vmax))
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Chrome trace_event spans

def tracing():
    """True while a trace capture is armed."""
    return _state.trace_active


def start_trace(path):
    """Arm span capture. ``path`` may be a directory (a
    ``mx_trace_<pid>.json`` file is created inside) or a ``.json``
    file path. Re-arming while active flushes the previous capture
    first. Automatically armed at import when ``MXNET_TRACE_DIR`` is
    set; flushed at interpreter exit."""
    if _state.trace_active:
        stop_trace()
    if path.endswith(".json"):
        # file form: make sure the flush destination can exist NOW —
        # discovering a missing parent directory at the atexit flush
        # would silently lose the whole capture
        parent = os.path.dirname(path)
        if parent:
            if os.path.exists(parent) and not os.path.isdir(parent):
                raise MXNetError(
                    "telemetry trace path %r: parent %r exists and is "
                    "not a directory" % (path, parent))
            os.makedirs(parent, exist_ok=True)
    else:
        # directory form — refuse loudly if the path is taken by a
        # plain file (os.makedirs would raise a bare FileExistsError)
        if os.path.exists(path) and not os.path.isdir(path):
            raise MXNetError(
                "telemetry trace path %r exists and is not a directory "
                "(pass a directory, or a path ending in .json)" % path)
        os.makedirs(path, exist_ok=True)
        path = os.path.join(path, "mx_trace_%d.json" % os.getpid())
    with _state.trace_lock:
        _state.trace_events = []
        _state.trace_dropped = 0
        _state.trace_path = path
        _state.trace_epoch = time.perf_counter()
        _state.trace_active = True
    return path


def stop_trace():
    """Disarm and flush the capture to its JSON file
    (``{"traceEvents": [...]}`` — the Chrome ``trace_event`` format
    Perfetto and chrome://tracing open directly). Returns the file
    path, or None when no capture was active."""
    with _state.trace_lock:
        if not _state.trace_active:
            return None
        _state.trace_active = False
        events, _state.trace_events = _state.trace_events, []
        dropped = _state.trace_dropped
        path = _state.trace_path
        _state.trace_path = None
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if dropped:
        doc["mxnetDroppedEvents"] = dropped
    with open(path, "w") as f:
        json.dump(doc, f)
    logging.info("telemetry: wrote %d trace events to %s%s",
                 len(events), path,
                 " (%d dropped at the buffer cap)" % dropped
                 if dropped else "")
    return path


def _emit(ev):
    with _state.trace_lock:
        if not _state.trace_active:
            return
        if len(_state.trace_events) >= _MAX_TRACE_EVENTS:
            _state.trace_dropped += 1
            return
        _state.trace_events.append(ev)


def trace_complete(name, t0, dur_s, cat="mx", args=None):
    """Low-level: record one complete ("X") span from a caller that
    timed itself (``t0`` = perf_counter at entry, ``dur_s`` seconds).
    Nesting in the viewer is positional: events on the same thread
    whose [ts, ts+dur] contain each other render nested — no parent
    bookkeeping needed."""
    if not (_state.enabled and _state.trace_active):
        return
    ev = {"name": name, "cat": cat, "ph": "X",
          "ts": (t0 - _state.trace_epoch) * 1e6,
          "dur": dur_s * 1e6,
          "pid": os.getpid(), "tid": threading.get_native_id()}
    if args:
        ev["args"] = args
    _emit(ev)


def mark(name, cat="mx", **args):
    """Record an instant event (compile, reconnect, crash-recovery —
    point-in-time happenings with no duration)."""
    if not (_state.enabled and _state.trace_active):
        return
    ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
          "ts": (time.perf_counter() - _state.trace_epoch) * 1e6,
          "pid": os.getpid(), "tid": threading.get_native_id()}
    if args:
        ev["args"] = args
    _emit(ev)


@contextlib.contextmanager
def tracing_paused():
    """Temporarily suppress span/mark emission without disarming the
    capture — for self-measuring code (bench A/B arms) whose own spans
    would be noise in the user's trace. Emission resumes on exit
    unless the capture was stopped inside the block."""
    with _state.trace_lock:
        was = _state.trace_active
        _state.trace_active = False
    try:
        yield
    finally:
        with _state.trace_lock:
            # stop_trace inside the block wins: resuming onto a
            # flushed capture would buffer events nobody ever writes
            _state.trace_active = was and _state.trace_path is not None


@contextlib.contextmanager
def span(name, cat="mx", hist=None, **args):
    """Time a region: always feeds ``hist`` (a :class:`Histogram`, in
    milliseconds) when given, and records a trace span while a capture
    is armed. Near-free when disabled (one flag check)."""
    if not _state.enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if hist is not None:
            hist.observe(dt * 1e3)
        if _state.trace_active:
            trace_complete(name, t0, dt, cat=cat, args=args or None)


# ---------------------------------------------------------------------------
# SLO accounting: multi-window burn rates over an existing histogram

class SloWindow:
    """Multi-window SLO burn-rate gauges computed from an existing
    cumulative :class:`Histogram` (doc/observability.md "SLO
    accounting").

    The histogram already holds every observation; what an SLO needs
    on top is *windowed attainment*: of the observations in the last
    W seconds, what fraction beat the target latency, and how fast is
    that burning the error budget? ``tick()`` samples the histogram's
    ``(count, count_le(threshold))`` pair on a bounded cadence and
    differences the samples per window:

        burn = (misses_in_window / observations_in_window)
               / (1 - target)

    so burn 1.0 = missing exactly the budgeted rate (e.g. 1% for
    target 0.99), burn 10 = burning budget 10x too fast — the
    standard multi-window multi-burn-rate alerting shape (SRE
    workbook ch. 5). The threshold is quantized UP to the histogram's
    next bucket bound (:meth:`Histogram.count_le`); windows with no
    observations read 0 (no traffic burns no budget).

    ``windows``: sequence of ``(seconds, Gauge)`` — the gauges are
    created by the caller with literal names so the metric catalog
    lint can see them. Host-side and allocation-bounded: one sample
    per ``min_interval_s`` at most, pruned past the longest window.
    """

    def __init__(self, hist, threshold, target=0.99, windows=(),
                 min_interval_s=1.0):
        if not 0.0 < float(target) < 1.0:
            raise MXNetError("SloWindow: target must be in (0, 1), "
                             "got %r" % (target,))
        self.hist = hist
        self.threshold = float(threshold)
        self.budget = 1.0 - float(target)
        self.windows = tuple(sorted(((float(w), g) for w, g in windows),
                                    key=lambda p: p[0]))
        self.min_interval_s = float(min_interval_s)
        self._samples = collections.deque()
        self._last = None
        # tick() is called from the owning loop AND from exposition-
        # server scrape threads; the deque iteration must not race a
        # concurrent append/popleft (the rate-limit check alone is
        # racy). Uncontended lock: ~100 ns, once per >= min_interval.
        self._lock = threading.Lock()

    def tick(self, now=None):
        """Sample the histogram and refresh every window's burn
        gauge. Rate-limited: calls within ``min_interval_s`` of the
        previous sample are free no-ops, so per-round callers don't
        accumulate unbounded samples. Thread-safe."""
        if not (_state.enabled and self.windows):
            return
        if now is None:
            now = time.perf_counter()
        with self._lock:
            self._tick_locked(now)

    def _tick_locked(self, now):
        if self._last is not None \
                and now - self._last < self.min_interval_s:
            return
        self._last = now
        # ok BEFORE total: the two reads are separate histogram lock
        # acquisitions, and an observe landing between them must err
        # toward counting the racing observation as a miss (bounded by
        # the clamp below) — the other order could read ok > total and
        # export a NEGATIVE burn rate
        ok = self.hist.count_le(self.threshold)
        total = self.hist.count
        self._samples.append((now, total, ok))
        horizon = now - self.windows[-1][0]
        # keep ONE sample at-or-before the horizon: it is the longest
        # window's baseline
        while len(self._samples) >= 2 and self._samples[1][0] <= horizon:
            self._samples.popleft()
        for w, g in self.windows:
            base = self._samples[0]
            for s in self._samples:
                if s[0] <= now - w:
                    base = s
                else:
                    break
            d_total = total - base[1]
            d_ok = ok - base[2]
            if d_total <= 0:
                g.set(0.0)
            else:
                miss_frac = min(1.0, max(
                    0.0, 1.0 - d_ok / float(d_total)))
                g.set(miss_frac / self.budget)


# ---------------------------------------------------------------------------
# HTTP exposition (mxnet_tpu/telemetry_http.py holds the server; these
# delegators keep the user-facing surface on mx.telemetry)

def serve(port=0, host="127.0.0.1"):
    """Start (or restart) the read-only HTTP exposition server on a
    daemon thread: ``GET /metrics`` (Prometheus text), ``/snapshot``
    (JSON), ``/requests`` / ``/flight/<id>`` (serving request table +
    per-request flight timelines), ``/healthz``. ``port=0`` binds an
    ephemeral port. Returns the server handle (``.url``, ``.port``,
    ``.stop()``). ``MXNET_TELEMETRY_PORT`` starts one at import. See
    doc/observability.md "The exposition server"."""
    from . import telemetry_http
    return telemetry_http.serve(port=port, host=host)


def stop_server():
    """Stop the exposition server if one is running (idempotent)."""
    from . import telemetry_http
    telemetry_http.stop_server()


# ---------------------------------------------------------------------------
# periodic logging reporter

def _summary_line():
    """One compact human line: every counter/gauge, histograms as
    count/mean/p99."""
    with _state.lock:
        items = sorted(_state.metrics.items())
    bits = []
    for name, m in items:
        if m.kind == "counter":
            if m.value:
                bits.append("%s=%d" % (name, m.value))
        elif m.kind == "gauge":
            if m.value:
                bits.append("%s=%.4g" % (name, m.value))
        elif m.count:
            bits.append("%s[n=%d mean=%.3g p99=%.3g]"
                        % (name, m.count, m.sum / m.count,
                           m.percentile(0.99)))
    return " ".join(bits) if bits else "(no activity)"


def start_reporter(interval_s, logger=None):
    """Log :func:`_summary_line` every ``interval_s`` seconds on a
    daemon thread (``MXNET_TELEMETRY_LOG_INTERVAL`` starts one at
    import). Restarting replaces the previous reporter."""
    stop_reporter()
    log = logger if logger is not None else logging.getLogger(__name__)
    stop = threading.Event()

    def run():
        while not stop.wait(interval_s):
            log.info("telemetry: %s", _summary_line())

    t = threading.Thread(target=run, daemon=True,
                         name="mx-telemetry-reporter")
    _state.reporter, _state.reporter_stop = t, stop
    t.start()
    return t


def stop_reporter():
    if _state.reporter_stop is not None:
        _state.reporter_stop.set()
        _state.reporter = None
        _state.reporter_stop = None


# ---------------------------------------------------------------------------
# import-time arming from the environment

# flush any still-armed capture at interpreter exit — covers both the
# MXNET_TRACE_DIR auto-arm below and a manual start_trace the caller
# forgot to stop (stop_trace is a no-op when nothing is active)
atexit.register(stop_trace)

_trace_dir = os.environ.get("MXNET_TRACE_DIR")
if _trace_dir:
    try:
        start_trace(_trace_dir)
    except Exception as _e:
        # a bad knob value must not take down `import mxnet_tpu`
        logging.warning("MXNET_TRACE_DIR=%r is unusable (%s) — trace "
                        "capture not armed", _trace_dir, _e)

_log_interval = os.environ.get("MXNET_TELEMETRY_LOG_INTERVAL")
if _log_interval:
    try:
        _iv = float(_log_interval)
    except ValueError:
        logging.warning("MXNET_TELEMETRY_LOG_INTERVAL=%r is not a "
                        "number; reporter not started", _log_interval)
    else:
        if _iv > 0:
            start_reporter(_iv)
