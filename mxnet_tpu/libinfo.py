"""Locate and load the native runtime library (parity:
``python/mxnet/libinfo.py`` + ``base.py`` _LIB loading).

The native library is optional: every consumer has a pure-Python fallback,
so an unbuilt tree still works (build with ``make -C cpp``).
"""
from __future__ import annotations

import ctypes
import os

_LIB = None
_TRIED = False


def find_lib_path():
    cur = os.path.dirname(os.path.abspath(__file__))
    candidates = [
        os.environ.get("MXNET_TPU_LIBRARY", ""),
        os.path.join(cur, "lib", "libmxnet_tpu.so"),
        os.path.join(cur, "..", "cpp", "libmxnet_tpu.so"),
    ]
    return [p for p in candidates if p and os.path.exists(p)]


def get_lib():
    """The loaded CDLL or None if unavailable."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    paths = find_lib_path()
    if not paths:
        return None
    try:
        lib = ctypes.CDLL(paths[0])
        lib.MXTGetLastError.restype = ctypes.c_char_p
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def check_call(ret):
    if ret != 0:
        lib = get_lib()
        msg = lib.MXTGetLastError().decode() if lib else "native call failed"
        from .base import MXNetError
        raise MXNetError(msg)
