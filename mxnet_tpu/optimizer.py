"""Weight-update optimizers.

Parity: ``/root/reference/python/mxnet/optimizer.py`` (registry + SGD:163,
SGLD:254, ccSGD:336, Adam:425, AdaGrad:550, RMSProp:586, AdaDelta:662,
Test:718, ``get_updater``) and ``src/optimizer/sgd-inl.h`` (momentum,
weight decay, gradient clipping, rescale).

TPU-first: each optimizer's math lives in a pure ``_step(weight, grad,
state, lr, wd)`` jax function. ``update()`` (the reference's imperative
entry point, used by KVStore updaters and tests) applies it eagerly to
NDArrays; the fused training path (model.py / parallel trainer) calls the
same pure math inside one jitted train step so the whole
forward+backward+update is a single XLA program.
"""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray, zeros
from . import random as mx_random

__all__ = ["Optimizer", "SGD", "SGLD", "ccSGD", "Adam", "AdaGrad", "RMSProp",
           "AdaDelta", "AdaFactor", "Test", "create", "get_updater",
           "register"]


class Optimizer:
    """Base optimizer with the reference's registry and lr-scale plumbing."""

    opt_registry = {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, rescale_grad=1.0, **kwargs):
        if name.lower() not in Optimizer.opt_registry:
            raise ValueError("Cannot find optimizer %s" % name)
        return Optimizer.opt_registry[name.lower()](
            rescale_grad=rescale_grad, **kwargs)

    def __init__(self, rescale_grad=1.0, arg_names=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None):
        self.sym = sym  # used by ccSGD in the reference; kept for parity
        self.rescale_grad = float(rescale_grad)
        self.lr = float(learning_rate)
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = float(wd)
        self.clip_gradient = clip_gradient
        self.num_update = 0
        self._index_update_count = {}
        self.lr_scale = {}
        self.idx2name = {}
        if arg_names is not None:
            self.idx2name = {i: n for i, n in enumerate(arg_names)}

    def set_lr_scale(self, args_lrscale):
        """Per-index lr multipliers (reference optimizer.py set_lr_scale)."""
        self.lr_scale = args_lrscale.copy()

    def set_lr_mult(self, args_lr_mult):
        self.lr_scale = args_lr_mult.copy()

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = 0
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        return lr * self.lr_scale.get(index, 1.0)

    # --- interface -----------------------------------------------------
    def create_state(self, index, weight):
        raise NotImplementedError

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    # --- pure-math helpers shared by eager and fused paths --------------
    def _clip_rescale(self, g):
        g = g * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g


register = Optimizer.register
create = Optimizer.create_optimizer


@register
class SGD(Optimizer):
    """SGD with momentum and weight decay (reference optimizer.py:163,
    src/optimizer/sgd-inl.h:21-161)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = float(momentum)

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, weight.context, dtype=weight.dtype)

    def _step(self, w, g, mom, lr, wd):
        g = self._clip_rescale(g)
        g = g + wd * w
        if mom is None:
            return w - lr * g, None
        new_mom = self.momentum * mom - lr * g
        return w + new_mom, new_mom

    def update(self, index, weight, grad, state):
        assert isinstance(weight, NDArray) and isinstance(grad, NDArray)
        lr = self._get_lr(index)
        self._update_count(index)
        new_w, new_mom = self._step(weight._val, grad._val,
                                    None if state is None else state._val,
                                    lr, self.wd)
        weight._set(new_w)
        if state is not None:
            state._set(new_mom)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference optimizer.py:254):
    SGD plus gaussian noise scaled by sqrt(lr)."""

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        self._update_count(index)
        g = self._clip_rescale(grad._val) + self.wd * weight._val
        noise = mx_random.normal(0, math.sqrt(lr), weight.shape,
                                 weight.context)
        weight._set(weight._val - (lr / 2) * g + noise._val)


@register
class ccSGD(SGD):
    """C++-implemented SGD in the reference (src/optimizer/sgd-inl.h);
    identical math to SGD here — there is no separate engine path."""


@register
class Adam(Optimizer):
    """Adam (reference optimizer.py:425; Kingma & Ba 2014) with the
    reference's time-step bias correction."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, decay_factor=(1 - 1e-8), **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.decay_factor = decay_factor

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),
                zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        self._update_count(index)
        # t = this weight's update round. The reference tracks one shared
        # ``time`` ("all parameters share the same time", optimizer.py:519)
        # whose lazy-create_state bookkeeping can desynchronize it across
        # params; the per-index count realizes the documented intent and is
        # what the fused (parallel.optim) path uses, so both paths agree.
        mean, var = state
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr_t = lr * math.sqrt(coef2) / coef1
        g = self._clip_rescale(grad._val) + self.wd * weight._val
        new_mean = self.beta1 * mean._val + (1 - self.beta1) * g
        new_var = self.beta2 * var._val + (1 - self.beta2) * g * g
        weight._set(weight._val - lr_t * new_mean /
                    (jnp.sqrt(new_var) + self.epsilon))
        mean._set(new_mean)
        var._set(new_var)


@register
class AdamW(Adam):
    """Adam with DECOUPLED weight decay (Loshchilov & Hutter 2017) — the
    transformer-training standard. No reference counterpart (2015): the
    reference's Adam folds wd into the gradient (L2), which interacts
    with the adaptive denominator; AdamW applies decay directly to the
    weight, scaled by the schedule lr but not by lr_t's bias correction.
    """

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        self._update_count(index)
        mean, var = state
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr_t = lr * math.sqrt(coef2) / coef1
        g = self._clip_rescale(grad._val)  # NO wd coupling
        new_mean = self.beta1 * mean._val + (1 - self.beta1) * g
        new_var = self.beta2 * var._val + (1 - self.beta2) * g * g
        weight._set(weight._val
                    - lr_t * new_mean / (jnp.sqrt(new_var) + self.epsilon)
                    - lr * self.wd * weight._val)
        mean._set(new_mean)
        var._set(new_var)


@register
class AdaFactor(Optimizer):
    """Adafactor (Shazeer & Stern 2018) — sublinear optimizer memory.

    For rank>=2 weights the second moment is stored as a rank-reduced
    ROW factor plus COLUMN factor (O(n+m) floats instead of O(nm); the
    reconstruction ``v ≈ r⊗c / mean(r)`` is exact when v is rank-1 and
    tight in practice), so e.g. a [32k, 768] embedding's state drops
    from 24.6M floats to 33k. The T5-era TPU optimizer; no reference
    counterpart (2015).

    Paper-recommended schedule: ``beta2_t = 1 - t^-decay_rate``, the
    update RMS-clipped at ``clipping_threshold``, and (with
    ``scale_by_param``) the step scaled by ``max(epsilon2, RMS(w))`` so
    steps are relative to weight magnitude. ``beta1>0`` adds
    first-moment momentum (off by default, as in the paper — that is
    where the memory saving comes from). Weight decay is decoupled
    (AdamW-style).
    """

    def __init__(self, learning_rate=0.01, beta1=0.0, decay_rate=0.8,
                 epsilon1=1e-30, epsilon2=1e-3, clipping_threshold=1.0,
                 scale_by_param=True, factored=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = float(beta1)
        self.decay_rate = float(decay_rate)
        self.epsilon1 = float(epsilon1)
        self.epsilon2 = float(epsilon2)
        self.clipping_threshold = float(clipping_threshold)
        self.scale_by_param = bool(scale_by_param)
        self.factored = bool(factored)

    def _factored(self, shape):
        return self.factored and len(shape) >= 2

    def create_state(self, index, weight):
        if self._factored(weight.shape):
            state = [zeros(weight.shape[:-1], weight.context,
                           dtype=weight.dtype),
                     zeros(weight.shape[:-2] + weight.shape[-1:],
                           weight.context, dtype=weight.dtype)]
        else:
            state = [zeros(weight.shape, weight.context,
                           dtype=weight.dtype)]
        if self.beta1 > 0:
            state.append(zeros(weight.shape, weight.context,
                               dtype=weight.dtype))
        return state

    def _step(self, w, g, state, lr, t):
        """Pure math on jax arrays; state is a list of arrays laid out
        as in create_state. Shared verbatim by the fused adapter."""
        g = self._clip_rescale(g)
        beta2t = 1.0 - t ** (-self.decay_rate)
        g2 = g * g + self.epsilon1
        if self._factored(w.shape):
            vr, vc = state[0], state[1]
            new_vr = beta2t * vr + (1 - beta2t) * g2.mean(axis=-1)
            new_vc = beta2t * vc + (1 - beta2t) * g2.mean(axis=-2)
            # v_hat = (vr ⊗ vc) / mean(vr): normalize the row factor so
            # the product has vc's scale
            r = new_vr / new_vr.mean(axis=-1, keepdims=True)
            u = g / (jnp.sqrt(r)[..., None]
                     * jnp.sqrt(new_vc)[..., None, :])
            new_state = [new_vr, new_vc]
        else:
            new_v = beta2t * state[0] + (1 - beta2t) * g2
            u = g / jnp.sqrt(new_v)
            new_state = [new_v]
        rms_u = jnp.sqrt(jnp.mean(u * u))
        u = u / jnp.maximum(1.0, rms_u / self.clipping_threshold)
        scale = lr
        if self.scale_by_param:
            scale = lr * jnp.maximum(self.epsilon2,
                                     jnp.sqrt(jnp.mean(w * w)))
        u = scale * u
        if self.beta1 > 0:
            new_m = self.beta1 * state[-1] + (1 - self.beta1) * u
            u = new_m
            new_state.append(new_m)
        return w - u - lr * self.wd * w, new_state

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        self._update_count(index)
        t = float(self._index_update_count[index])
        new_w, new_state = self._step(
            weight._val, grad._val, [s._val for s in state], lr, t)
        weight._set(new_w)
        for s, v in zip(state, new_state):
            s._set(v)


@register
class AdaGrad(Optimizer):
    """AdaGrad (reference optimizer.py:550; Duchi et al. 2011)."""

    def __init__(self, learning_rate=0.05, eps=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        self._update_count(index)
        g = self._clip_rescale(grad._val)
        hist = state._val + g * g
        state._set(hist)
        weight._set(weight._val - lr *
                    (g / jnp.sqrt(hist + self.float_stable_eps)
                     + self.wd * weight._val))


@register
class RMSProp(Optimizer):
    """RMSProp (reference optimizer.py:586; Tieleman & Hinton lecture,
    with the Graves-style momentum terms gamma1/gamma2)."""

    def __init__(self, learning_rate=0.002, gamma1=0.95, gamma2=0.9,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),  # n
                zeros(weight.shape, weight.context, dtype=weight.dtype),  # g
                zeros(weight.shape, weight.context, dtype=weight.dtype))  # delta

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        self._update_count(index)
        n, g_avg, delta = state
        g = self._clip_rescale(grad._val) + self.wd * weight._val
        new_n = (1 - self.gamma1) * g * g + self.gamma1 * n._val
        new_g = (1 - self.gamma1) * g + self.gamma1 * g_avg._val
        new_delta = self.gamma2 * delta._val - lr * g / jnp.sqrt(
            new_n - new_g * new_g + 1e-4)
        n._set(new_n)
        g_avg._set(new_g)
        delta._set(new_delta)
        weight._set(weight._val + new_delta)


@register
class AdaDelta(Optimizer):
    """AdaDelta (reference optimizer.py:662; Zeiler 2012)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),
                zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        acc_g, acc_delta = state
        g = self._clip_rescale(grad._val)
        new_acc_g = self.rho * acc_g._val + (1 - self.rho) * g * g
        current_delta = jnp.sqrt(acc_delta._val + self.epsilon) / \
            jnp.sqrt(new_acc_g + self.epsilon) * g
        new_acc_delta = self.rho * acc_delta._val + \
            (1 - self.rho) * current_delta * current_delta
        acc_g._set(new_acc_g)
        acc_delta._set(new_acc_delta)
        weight._set(weight._val - self.wd * weight._val - current_delta)


@register
class Test(Optimizer):
    """Test optimizer: w -= rescale*grad (reference optimizer.py:718)."""

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        weight._set(weight._val - grad._val * self.rescale_grad)
        state._set(weight._val)


def _state_to_host(state):
    """Optimizer state -> picklable host structure (NDArray leaves become
    numpy; tuple/list/None structure is preserved)."""
    if state is None:
        return None
    if isinstance(state, (tuple, list)):
        return type(state)(_state_to_host(s) for s in state)
    if isinstance(state, NDArray):
        return state.asnumpy()
    return np.asarray(state)


def _state_from_host(state):
    """Inverse of :func:`_state_to_host`."""
    from . import ndarray as nd
    if state is None:
        return None
    if isinstance(state, (tuple, list)):
        return type(state)(_state_from_host(s) for s in state)
    return nd.array(np.asarray(state))


def get_updater(optimizer):
    """Close an optimizer into updater(index, grad, weight) with lazily
    created per-index state (reference optimizer.py get_updater).

    ``get_states()``/``set_states()`` (reference updater.get_states /
    set_states) snapshot and restore the per-index state PLUS the
    optimizer's update counts (adam bias correction, lr schedules), so a
    crash-resumed run continues the exact same optimizer trajectory."""
    states = {}

    def updater(index, grad, weight):
        if index not in states:
            states[index] = optimizer.create_state(index, weight)
        optimizer.update(index, weight, grad, states[index])

    def get_states():
        return {
            "states": {k: _state_to_host(v) for k, v in states.items()},
            "update_count": dict(optimizer._index_update_count),
            "num_update": optimizer.num_update,
        }

    def set_states(blob):
        states.clear()
        states.update({k: _state_from_host(v)
                       for k, v in blob["states"].items()})
        optimizer._index_update_count = dict(blob["update_count"])
        optimizer.num_update = blob["num_update"]

    updater.states = states
    updater.optimizer = optimizer
    updater.get_states = get_states
    updater.set_states = set_states
    return updater
