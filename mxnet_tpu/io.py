"""Data iterators.

Parity: ``/root/reference/python/mxnet/io.py`` (DataIter protocol,
NDArrayIter:311, ResizeIter:112, PrefetchingIter:166) and the C++ iterators
``src/io/iter_mnist.cc`` (MNISTIter) and ``src/io/iter_csv.cc`` (CSVIter).
The RecordIO image pipeline lives in recordio.py / image_io.py.

TPU-first: batches are staged host-side in numpy and device_put at
``getdata``; PrefetchingIter overlaps host decode with device compute the
way the reference's dmlc::ThreadedIter prefetcher does
(``src/io/iter_prefetcher.h``). Distributed sharding uses the reference's
``num_parts``/``part_index`` convention.
"""
from __future__ import annotations

import gzip
import os
import struct
import threading

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["MXDataIter", "DataIter", "DataBatch", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "MNISTIter", "CSVIter"]


class DataBatch:
    """One batch: data/label NDArray lists + index + pad
    (reference ``include/mxnet/io.h`` DataBatch)."""

    def __init__(self, data, label, pad=None, index=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index


class DataIter:
    """Iterator protocol (reference io.py:DataIter): provide_data/
    provide_label/batch_size + reset/iter_next/getdata/getlabel/getindex/
    getpad; supports both the next() protocol and callback iteration."""

    def __init__(self):
        self.batch_size = 0

    def reset(self):
        pass

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize input data to list of (name, numpy) (reference io.py)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d
                    for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, list or dict")
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, np.asarray(v)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference io.py:311).

    ``last_batch_handle``: 'pad' (wrap around, batch.pad reports overlap),
    'discard' (drop tail), 'roll_over' (reference semantics: leftover rolls
    to next epoch).
    """

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__()
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size."
        self.num_source = len(self.data)

        if shuffle:
            idx = np.arange(self.num_data)
            np.random.shuffle(idx)
            self.data = [(k, v[idx]) for k, v in self.data]
            self.label = [(k, v[idx]) for k, v in self.label]

        if last_batch_handle == "discard":
            new_n = self.num_data - self.num_data % batch_size
            self.num_data = new_n

        self.batch_size = batch_size
        self.cursor = -batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [(k, (self.batch_size,) + v.shape[1:]) for k, v in self.data]

    @property
    def provide_label(self):
        return [(k, (self.batch_size,) + v.shape[1:]) for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) \
                % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [nd.array(v[self.cursor:self.cursor + self.batch_size])
                    for _, v in data_source]
        pad = self.batch_size - self.num_data + self.cursor
        return [nd.array(np.concatenate([v[self.cursor:], v[:pad]], axis=0))
                for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches per epoch
    (reference io.py:112)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Thread-pipelined prefetcher over one or more iterators (reference
    io.py:166; C++ analogue iter_prefetcher.h dmlc::ThreadedIter).

    Overlaps host-side batch preparation with device compute — the same
    cross-step overlap the reference's engine provides.
    """

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]

        def prefetch_func(self, i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=[self, i], daemon=True)
            for i in range(self.n_iter)]
        for t in self.prefetch_threads:
            t.start()

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[(r[n], s) for n, s in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[(r[n], s) for n, s in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, "Number of entry mismatches between iterators"
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, \
                "Number of entry mismatches between iterators"
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad,
            self.next_batch[0].index)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


def _read_idx_images(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise MXNetError("bad MNIST image file %s" % path)
        data = np.frombuffer(f.read(num * rows * cols), dtype=np.uint8)
        return data.reshape(num, rows, cols)


def _read_idx_labels(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, num = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise MXNetError("bad MNIST label file %s" % path)
        return np.frombuffer(f.read(num), dtype=np.uint8)


class MNISTIter(NDArrayIter):
    """idx-format MNIST reader (reference src/io/iter_mnist.cc): shuffle,
    flat vs (1,28,28), distributed num_parts/part_index sharding."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 silent=False, seed=0, input_shape=None,
                 num_parts=1, part_index=0, **kwargs):
        images = _read_idx_images(image).astype(np.float32) / 255.0
        labels = _read_idx_labels(label).astype(np.float32)
        if flat:
            images = images.reshape(len(images), -1)
        else:
            images = images.reshape(len(images), 1,
                                    images.shape[1], images.shape[2])
        if input_shape is not None:
            images = images.reshape((len(images),) + tuple(input_shape))
        if shuffle:
            rs = np.random.RandomState(seed)
            idx = rs.permutation(len(images))
            images, labels = images[idx], labels[idx]
        if num_parts > 1:  # worker sharding (iter_mnist.cc partitioning)
            images = images[part_index::num_parts]
            labels = labels[part_index::num_parts]
        super().__init__(images, labels, batch_size=batch_size, shuffle=False,
                         last_batch_handle="discard")


class CSVIter(NDArrayIter):
    """CSV reader (reference src/io/iter_csv.cc): data_csv/label_csv with
    declared shapes."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=128, round_batch=True, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        else:
            label = np.zeros(data.shape[0], dtype=np.float32)
        super().__init__(
            data, label, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard")


def _imagerecorditer(*args, **kwargs):
    """mx.io.ImageRecordIter (native pipeline; see image_io.py)."""
    from .image_io import ImageRecordIter as _IRI
    return _IRI(*args, **kwargs)


ImageRecordIter = _imagerecorditer


class MXDataIter(DataIter):
    """Wrap a DataIterHandle from the native C graph ABI (reference
    io.py:426: MXDataIter wraps C-registered iterators).

    ``handle`` is the opaque id returned by ``MXTDataIterCreateIter`` /
    ``c_api_impl.data_iter_create``, so iterators created through the C
    ABI and Python code can share state. Prefer the direct classes
    (MNISTIter/CSVIter/ImageRecordIter) in pure-Python programs.
    """

    def __init__(self, handle, data_name="data",
                 label_name="softmax_label"):
        from . import c_api_impl as _impl
        self._impl = _impl
        self.handle = int(handle)
        super().__init__()
        inner = _impl._get(self.handle)
        self.batch_size = getattr(inner, "batch_size", 0)
        self.data_name = data_name
        self.label_name = label_name

    def reset(self):
        self._impl.data_iter_before_first(self.handle)

    def iter_next(self):
        return bool(self._impl.data_iter_next(self.handle))

    def getdata(self):
        hid = self._impl.data_iter_get_data(self.handle)
        try:
            return [self._impl._get(hid)]  # list, like NDArrayIter
        finally:
            self._impl.free_handle(hid)

    def getlabel(self):
        hid = self._impl.data_iter_get_label(self.handle)
        try:
            return [self._impl._get(hid)]
        finally:
            self._impl.free_handle(hid)

    def getindex(self):
        idx = self._impl.data_iter_get_index(self.handle)
        return np.asarray(idx) if idx else None

    def getpad(self):
        return self._impl.data_iter_get_pad(self.handle)

    @property
    def provide_data(self):
        return getattr(self._impl._get(self.handle), "provide_data", None)

    @property
    def provide_label(self):
        return getattr(self._impl._get(self.handle), "provide_label", None)

    def __del__(self):
        try:
            self._impl.free_handle(self.handle)
        except Exception:  # interpreter shutdown
            pass
