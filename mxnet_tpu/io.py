"""Data iterators.

Parity: ``/root/reference/python/mxnet/io.py`` (DataIter protocol,
NDArrayIter:311, ResizeIter:112, PrefetchingIter:166) and the C++ iterators
``src/io/iter_mnist.cc`` (MNISTIter) and ``src/io/iter_csv.cc`` (CSVIter).
The RecordIO image pipeline lives in recordio.py / image_io.py.

TPU-first: batches are staged host-side in numpy and device_put at
``getdata``; PrefetchingIter overlaps host decode with device compute the
way the reference's dmlc::ThreadedIter prefetcher does
(``src/io/iter_prefetcher.h``). Distributed sharding uses the reference's
``num_parts``/``part_index`` convention.
"""
from __future__ import annotations

import collections
import gzip
import os
import struct
import queue
import threading
import time

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray
from . import telemetry as tele

# pipeline-thread metrics (doc/observability.md "IO pipeline"): fetch =
# host work done ON the pipeline thread (decode/augment/collate +
# transform, e.g. the staging device_put dispatch); wait = what the
# CONSUMER paid because that work wasn't ready — starvation
_TM_FETCH_MS = tele.histogram("io.pipeline_fetch_ms")
_TM_WAIT_MS = tele.histogram("io.pipeline_wait_ms")
_TM_STARVED = tele.counter("io.pipeline_starved")
_TM_BATCHES = tele.counter("io.pipeline_batches")

__all__ = ["MXDataIter", "DataIter", "DataBatch", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "DevicePrefetchIter", "StagedStream",
           "MNISTIter", "CSVIter"]


class DataBatch:
    """One batch: data/label NDArray lists + index + pad
    (reference ``include/mxnet/io.h`` DataBatch)."""

    def __init__(self, data, label, pad=None, index=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index


class DataIter:
    """Iterator protocol (reference io.py:DataIter): provide_data/
    provide_label/batch_size + reset/iter_next/getdata/getlabel/getindex/
    getpad; supports both the next() protocol and callback iteration."""

    def __init__(self):
        self.batch_size = 0

    def reset(self):
        pass

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize input data to list of (name, numpy) (reference io.py)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d
                    for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, list or dict")
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, np.asarray(v)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference io.py:311).

    ``last_batch_handle``: 'pad' (wrap around, batch.pad reports overlap),
    'discard' (drop tail), 'roll_over' (reference semantics: leftover rolls
    to next epoch).
    """

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__()
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size."
        self.num_source = len(self.data)

        if shuffle:
            idx = np.arange(self.num_data)
            np.random.shuffle(idx)
            self.data = [(k, v[idx]) for k, v in self.data]
            self.label = [(k, v[idx]) for k, v in self.label]

        if last_batch_handle == "discard":
            new_n = self.num_data - self.num_data % batch_size
            self.num_data = new_n

        self.batch_size = batch_size
        self.cursor = -batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [(k, (self.batch_size,) + v.shape[1:]) for k, v in self.data]

    @property
    def provide_label(self):
        return [(k, (self.batch_size,) + v.shape[1:]) for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) \
                % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [nd.array(v[self.cursor:self.cursor + self.batch_size])
                    for _, v in data_source]
        pad = self.batch_size - self.num_data + self.cursor
        return [nd.array(np.concatenate([v[self.cursor:], v[:pad]], axis=0))
                for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Redefine an iterator's epoch as exactly ``size`` batches
    (reference io.py:112 semantics): shorter epochs stop early, longer
    ones restart the wrapped iterator mid-epoch as needed.

    ``reset_internal=False`` decouples the two epoch notions entirely —
    the wrapped iterator keeps its own position across our resets.
    """

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        # batch geometry is whatever the wrapped iterator provides
        for attr in ("provide_data", "provide_label", "batch_size"):
            setattr(self, attr, getattr(data_iter, attr))

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def _draw(self):
        """Next batch from the wrapped iterator, restarting it at
        epoch boundaries so our own epoch length is ``size`` alone."""
        try:
            return self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            return self.data_iter.next()

    def iter_next(self):
        if self.cur >= self.size:
            return False
        self.cur += 1
        self.current_batch = self._draw()
        return True

    # the wrapped batch is passed through whole
    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class _WorkerFailure:
    """An exception that escaped a pipeline worker, carried through the
    response queue so the consumer can re-raise it loudly (a silently
    dead worker would otherwise hang the consumer on an empty queue)."""

    def __init__(self, exc):
        import traceback
        self.exc = exc
        self.tb = traceback.format_exc()


class _PipelineWorker(threading.Thread):
    """Depth-k producer for one iterator: a request/response channel.

    The consumer keeps up to ``depth`` fetch requests outstanding, so
    the wrapped iterator's host-side work (decode, augment, collate —
    plus an optional ``transform``, e.g. the device-staging
    ``jax.device_put``) runs while previous batches are being consumed.
    This is the shared queue/lifecycle machinery behind
    ``PrefetchingIter`` (depth 1, host pipelining) and
    ``DevicePrefetchIter`` (depth k, host→device staging).
    """

    _FETCH, _RESTART, _QUIT = object(), object(), object()

    def __init__(self, it, depth=1, transform=None):
        super().__init__(daemon=True)
        self._it = it
        self._transform = transform
        self._depth = max(1, int(depth))
        self._requests = queue.Queue()   # unbounded: posting never blocks
        self._results = queue.Queue()
        self._inflight = self._depth     # fetches requested/in flight
        self._ended = False              # consumer saw the epoch end
        self.start()
        for _ in range(self._depth):     # pipeline primed at construction
            self._requests.put(self._FETCH)

    def run(self):
        exhausted = False  # latched at epoch end: with depth > 1 there
        # are still outstanding fetch requests when StopIteration first
        # fires, and they must NOT touch the iterator again (NDArrayIter
        # roll_over cursors would advance twice)
        while True:
            req = self._requests.get()
            if req is self._QUIT:
                return
            if req is self._RESTART:
                exhausted = False
                continue
            if exhausted:
                self._results.put(None)
                continue
            try:
                tic = time.perf_counter()
                batch = self._it.next()
                if self._transform is not None:
                    batch = self._transform(batch)
                _TM_FETCH_MS.observe((time.perf_counter() - tic) * 1e3)
            except StopIteration:
                exhausted = True
                batch = None             # epoch-boundary marker
            except BaseException as e:   # surfaced, never a hung queue
                exhausted = True
                batch = _WorkerFailure(e)
            self._results.put(batch)

    def take(self):
        """Collect the oldest in-flight batch and post the next request —
        but NOT past an epoch end: after None the wrapped iterator must
        not be touched again until restart()."""
        if self._ended:
            return None                  # exhausted, awaiting restart()
        tic = time.perf_counter()
        batch = self._results.get()
        if batch is not None and not isinstance(batch, _WorkerFailure):
            # real batches only: waiting on the epoch-end None marker
            # (or a failure) is not input starvation — same exemption
            # the trainer-side input_wait probe applies to StopIteration
            wait = time.perf_counter() - tic
            _TM_WAIT_MS.observe(wait * 1e3)
            if wait > 1e-3:              # consumer actually stalled
                _TM_STARVED.inc()
            _TM_BATCHES.inc()
        if isinstance(batch, _WorkerFailure):
            self._ended = True
            self._absorb()
            raise MXNetError("data pipeline worker failed:\n%s"
                             % batch.tb) from batch.exc
        if batch is None:
            self._ended = True
            # later in-flight results are all None (the run loop latches
            # at the first StopIteration); absorb them now
            self._absorb()
        else:
            self._requests.put(self._FETCH)
        return batch

    def _absorb(self, first=None):
        """Drain in-flight responses down to zero (epoch end / restart);
        the caller has already taken one of them (``first``). Returns
        the first _WorkerFailure seen, if any — a failure must not be
        silently discarded by a reset racing it."""
        failure = first if isinstance(first, _WorkerFailure) else None
        drained = 1
        while drained < self._inflight:
            got = self._results.get()
            if failure is None and isinstance(got, _WorkerFailure):
                failure = got
            drained += 1
        self._inflight = 0
        return failure

    def restart(self):
        """Absorb in-flight fetches, rewind the iterator, re-prime. A
        worker failure sitting unconsumed in the response queue is
        re-raised here rather than swallowed."""
        failure = None
        if not self._ended:
            failure = self._absorb(self._results.get())
        # the worker is now idle (every request it will ever see has
        # been answered), so resetting from this thread cannot race it
        self._it.reset()
        self._requests.put(self._RESTART)
        self._ended = False
        self._inflight = self._depth
        for _ in range(self._depth):
            self._requests.put(self._FETCH)
        if failure is not None:
            raise MXNetError("data pipeline worker failed:\n%s"
                             % failure.tb) from failure.exc

    def stop(self):
        self._requests.put(self._QUIT)


class PrefetchingIter(DataIter):
    """Host-pipelined prefetcher over one or more iterators (the role of
    reference io.py:166 / dmlc::ThreadedIter in iter_prefetcher.h):
    batch i+1 is prepared by worker threads while batch i is in use,
    overlapping input preparation with device compute.

    Built from one ``_PipelineWorker`` queue pair per wrapped iterator;
    an epoch boundary travels through the response stream as ``None``
    from every worker at once. ``rename_data``/``rename_label`` remap
    the provided names per iterator (one dict each), letting several
    sources feed differently-named model inputs.
    """

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        self.iters = iters if isinstance(iters, list) else [iters]
        assert self.iters, "PrefetchingIter needs at least one iterator"
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.current_batch = None
        self._workers = [_PipelineWorker(i) for i in self.iters]

    def __del__(self):
        for w in getattr(self, "_workers", []):
            w.stop()

    @staticmethod
    def _combined(provides, renames):
        if renames is None:
            return [entry for p in provides for entry in p]
        return [(r[name], shape) for r, p in zip(renames, provides)
                for name, shape in p]

    @property
    def provide_data(self):
        return self._combined([i.provide_data for i in self.iters],
                              self.rename_data)

    @property
    def provide_label(self):
        return self._combined([i.provide_label for i in self.iters],
                              self.rename_label)

    def reset(self):
        for w in self._workers:
            w.restart()

    def iter_next(self):
        parts = [w.take() for w in self._workers]
        ended = [p is None for p in parts]
        if any(ended):
            assert all(ended), "iterators ended at different batch counts"
            return False
        assert all(p.pad == parts[0].pad for p in parts), \
            "iterators disagree on batch padding"
        self.current_batch = DataBatch(
            [d for p in parts for d in p.data],
            [l for p in parts for l in p.label],
            parts[0].pad, parts[0].index)
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class StagedStream:
    """THE depth-k staging helper: pull items from a source, run
    ``place`` on each (typically an async-dispatching
    ``jax.device_put``, so the transfer proceeds while earlier items
    are consumed), and keep up to ``depth`` placed items ready ahead
    of the consumer.

    One implementation behind three consumers (PR 2 recorded the first
    two as separate copies — debt paid here):

    * ``ParallelTrainer.staged_batches`` — fused train loops
      (``thread=False``),
    * ``DevicePrefetchIter`` — DataIter protocol over a pipeline
      thread (``thread=True``),
    * the serving engine's prompt stager
      (``mxnet_tpu/serving/engine.py`` — padded prompt h2d dispatched
      while decode steps run).

    ``source``: an object with ``.next()`` raising ``StopIteration``
    at the end and ``.reset()`` (any DataIter qualifies; small
    adapters suffice elsewhere).

    ``thread=False`` (default): items are pulled and placed inline
    when the consumer asks for the NEXT item — overlap comes purely
    from async dispatch, so the source itself must be cheap (host
    batches already in memory). Iteration ends at source exhaustion
    and then RE-ARMS (a new for-loop resumes); items staged before a
    consumer ``break`` are served on resume, never dropped.

    ``thread=True``: pulls + placement run on a ``_PipelineWorker``
    pipeline thread — for sources that do real host work (decode
    pools, augmentation). After exhaustion ``next()`` keeps raising
    ``StopIteration`` until ``reset()`` (DataIter epoch semantics);
    failures inside the threaded pull surface as ``MXNetError``.

    ``live_source=True`` (inline mode only): the source may GAIN items
    at any time (the serving engine's pending queue), so exhaustion is
    never latched — every fill re-probes the source, and a ``next()``
    right after new items arrive stages them immediately. The default
    (False) latches until the staged queue drains, which DataIter
    epoch semantics require: an exhausted epoch iterator must not be
    pulled again mid-drain (NDArrayIter roll_over cursors would
    advance twice).
    """

    def __init__(self, source, place=None, depth=2, thread=False,
                 live_source=False):
        self._source = source
        self._placefn = place if place is not None else (lambda x: x)
        self._depth = max(1, int(depth))
        self._live = bool(live_source)
        self._threaded = bool(thread)
        if self._threaded:
            self._worker = _PipelineWorker(source, depth=self._depth,
                                           transform=self._placefn)
        else:
            self._queue = collections.deque()
            self._exhausted = False

    # -- consumer side --------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        if self._threaded:
            got = self._worker.take()
            if got is None:
                raise StopIteration
            return got
        self._fill()
        if not self._queue:
            self._exhausted = False  # re-arm: caller resets + re-iterates
            raise StopIteration
        out = self._queue.popleft()
        self._fill()  # dispatch i+1's placement before handing back i
        return out

    def staged(self):
        """Items pulled from the source and staged but not yet handed
        to the consumer (inline mode; the threaded pipeline keeps its
        own in-flight accounting)."""
        return 0 if self._threaded else len(self._queue)

    def _fill(self):
        while not self._exhausted and len(self._queue) < self._depth:
            try:
                item = self._source.next()
            except StopIteration:
                if not self._live:
                    self._exhausted = True
                return
            self._queue.append(self._placefn(item))

    def prune(self, pred):
        """Drop staged items matching ``pred`` (inline mode only) and
        return them — the serving engine retires queue-waiting requests
        (deadline expiry, cancellation, load shedding) that its stager
        already pulled and placed, without disturbing the rest of the
        staged order."""
        if self._threaded:
            raise MXNetError("StagedStream.prune: inline mode only "
                             "(threaded staging owns its queue)")
        kept, dropped = [], []
        for x in self._queue:        # single pass: pred may be stateful
            (dropped if pred(x) else kept).append(x)
        if dropped:
            self._queue.clear()
            self._queue.extend(kept)
        return dropped

    # -- lifecycle ------------------------------------------------------
    def reset(self):
        """Discard staged items (stale after a source rewind) and
        rewind the source."""
        if self._threaded:
            self._worker.restart()   # absorbs in-flight + resets source
            return
        self._queue.clear()
        self._source.reset()
        self._exhausted = False

    def close(self):
        if self._threaded:
            self._worker.stop()


def _stage_nd(arr, sharding):
    """One array to a device/sharding, as an NDArray (async dispatch).
    Module-level so the staging transform does not capture the iterator
    (see DevicePrefetchIter.__init__)."""
    import jax

    ctx = None
    if isinstance(arr, NDArray):
        ctx = arr.context
        arr = arr._val
    return NDArray._from_jax(jax.device_put(arr, sharding), ctx)


class DevicePrefetchIter(DataIter):
    """Overlapped host→device staging over any DataIter: a pipeline
    thread pulls batch i+1 from ``base`` and ``jax.device_put``s it
    (async dispatch) while batch i is being consumed by the train step —
    the device half of the reference's ``iter_prefetcher.h`` double
    buffer, with the h2d copy itself moved off the consumer thread.

    ``depth`` batches are kept in flight (2 = classic double buffer).
    ``sharding`` places each array for the multi-chip path: pass a
    ``jax.sharding.Sharding`` directly, or ``mesh=`` (with
    ``data_axis``, default ``"dp"``) to shard dim 0 — the batch axis —
    across the mesh the way ``ParallelTrainer`` expects its inputs.
    Default: committed to the first local device.

    Composes on either side of ``DeviceAugmentIter``: wrap the augment
    iterator and its uint8 h2d + on-device augment both run on the
    pipeline thread, overlapped with compute. Pad and index propagate
    through unchanged.
    """

    def __init__(self, base, depth=2, sharding=None, mesh=None,
                 data_axis="dp"):
        import jax

        super().__init__()
        self._base = base
        self.batch_size = base.batch_size
        if sharding is None and mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            sharding = NamedSharding(mesh, PartitionSpec(data_axis))
        if sharding is None:
            sharding = jax.devices()[0]
        self._sharding = sharding
        self._current = None

        def stage(batch, _sh=sharding):
            # closes over the sharding only, NOT self: the pipeline
            # thread holds this transform, and a self-reference would
            # root the iterator forever — __del__ could never fire and
            # every dropped iterator would leak its thread (and any
            # decode pool underneath) until process exit
            return DataBatch([_stage_nd(d, _sh) for d in batch.data],
                             [_stage_nd(l, _sh) for l in batch.label],
                             batch.pad, batch.index)

        self._stream = StagedStream(base, place=stage, depth=depth,
                                    thread=True)
        self._worker = self._stream._worker  # the pipeline Thread

    def close(self):
        """Stop the pipeline thread (also run by ``__del__``; the
        thread itself is a daemon, so this is for promptness, not
        correctness)."""
        s = getattr(self, "_stream", None)
        if s is not None:
            s.close()

    def __del__(self):
        self.close()

    @property
    def provide_data(self):
        return self._base.provide_data

    @property
    def provide_label(self):
        return self._base.provide_label

    def reset(self):
        self._stream.reset()

    def iter_next(self):
        try:
            batch = self._stream.next()
        except StopIteration:
            batch = None
        self._current = batch
        return batch is not None

    def getdata(self):
        return self._current.data

    def getlabel(self):
        return self._current.label

    def getindex(self):
        return self._current.index

    def getpad(self):
        return self._current.pad


def _read_idx_images(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise MXNetError("bad MNIST image file %s" % path)
        data = np.frombuffer(f.read(num * rows * cols), dtype=np.uint8)
        return data.reshape(num, rows, cols)


def _read_idx_labels(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, num = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise MXNetError("bad MNIST label file %s" % path)
        return np.frombuffer(f.read(num), dtype=np.uint8)


class MNISTIter(NDArrayIter):
    """idx-format MNIST reader (reference src/io/iter_mnist.cc): shuffle,
    flat vs (1,28,28), distributed num_parts/part_index sharding."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 silent=False, seed=0, input_shape=None,
                 num_parts=1, part_index=0, **kwargs):
        images = _read_idx_images(image).astype(np.float32) / 255.0
        labels = _read_idx_labels(label).astype(np.float32)
        if flat:
            images = images.reshape(len(images), -1)
        else:
            images = images.reshape(len(images), 1,
                                    images.shape[1], images.shape[2])
        if input_shape is not None:
            images = images.reshape((len(images),) + tuple(input_shape))
        if shuffle:
            rs = np.random.RandomState(seed)
            idx = rs.permutation(len(images))
            images, labels = images[idx], labels[idx]
        if num_parts > 1:  # worker sharding (iter_mnist.cc partitioning)
            images = images[part_index::num_parts]
            labels = labels[part_index::num_parts]
        super().__init__(images, labels, batch_size=batch_size, shuffle=False,
                         last_batch_handle="discard")


class CSVIter(NDArrayIter):
    """CSV reader (reference src/io/iter_csv.cc): data_csv/label_csv with
    declared shapes."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=128, round_batch=True, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        else:
            label = np.zeros(data.shape[0], dtype=np.float32)
        super().__init__(
            data, label, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard")


def _imagerecorditer(*args, **kwargs):
    """mx.io.ImageRecordIter (native pipeline; see image_io.py)."""
    from .image_io import ImageRecordIter as _IRI
    return _IRI(*args, **kwargs)


ImageRecordIter = _imagerecorditer


class MXDataIter(DataIter):
    """Wrap a DataIterHandle from the native C graph ABI (reference
    io.py:426: MXDataIter wraps C-registered iterators).

    ``handle`` is the opaque id returned by ``MXTDataIterCreateIter`` /
    ``c_api_impl.data_iter_create``, so iterators created through the C
    ABI and Python code can share state. Prefer the direct classes
    (MNISTIter/CSVIter/ImageRecordIter) in pure-Python programs.
    """

    def __init__(self, handle, data_name="data",
                 label_name="softmax_label"):
        from . import c_api_impl as _impl
        self._impl = _impl
        self.handle = int(handle)
        super().__init__()
        inner = _impl._get(self.handle)
        self.batch_size = getattr(inner, "batch_size", 0)
        self.data_name = data_name
        self.label_name = label_name

    def reset(self):
        self._impl.data_iter_before_first(self.handle)

    def iter_next(self):
        return bool(self._impl.data_iter_next(self.handle))

    def getdata(self):
        hid = self._impl.data_iter_get_data(self.handle)
        try:
            return [self._impl._get(hid)]  # list, like NDArrayIter
        finally:
            self._impl.free_handle(hid)

    def getlabel(self):
        hid = self._impl.data_iter_get_label(self.handle)
        try:
            return [self._impl._get(hid)]
        finally:
            self._impl.free_handle(hid)

    def getindex(self):
        idx = self._impl.data_iter_get_index(self.handle)
        return np.asarray(idx) if idx else None

    def getpad(self):
        return self._impl.data_iter_get_pad(self.handle)

    @property
    def provide_data(self):
        return getattr(self._impl._get(self.handle), "provide_data", None)

    @property
    def provide_label(self):
        return getattr(self._impl._get(self.handle), "provide_label", None)

    def __del__(self):
        try:
            self._impl.free_handle(self.handle)
        except Exception:  # interpreter shutdown
            pass
