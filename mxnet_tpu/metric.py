"""Evaluation metrics.

Parity: ``/root/reference/python/mxnet/metric.py`` — EvalMetric base,
Accuracy, F1, MAE/MSE/RMSE, CrossEntropy, CustomMetric and the ``np``
decorator helper; ``create`` by-name factory.
"""
from __future__ import annotations

import numpy

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["Torch", "check_label_shapes", "EvalMetric", "Accuracy", "F1", "MAE", "MSE", "RMSE",
           "CrossEntropy", "TopKAccuracy", "Loss", "CustomMetric",
           "create", "np"]


def _as_numpy(x):
    return x.asnumpy() if isinstance(x, NDArray) else numpy.asarray(x)


class EvalMetric:
    """Base metric accumulating (sum_metric, num_inst)."""

    def __init__(self, name):
        self.name = name
        self.reset()

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst


class Accuracy(EvalMetric):
    """Classification accuracy (argmax over axis 1)."""

    def __init__(self):
        super().__init__("accuracy")

    def update(self, labels, preds):
        if len(labels) != len(preds):
            raise MXNetError("labels and preds length mismatch")
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype(numpy.int32)
            pred_label = numpy.argmax(pred, axis=1)
            self.sum_metric += int((pred_label.flat == label.flat).sum())
            self.num_inst += len(pred_label.flat)


class F1(EvalMetric):
    """Binary F1 score (reference metric.py:83)."""

    def __init__(self):
        super().__init__("f1")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype(numpy.int32)
            pred_label = numpy.argmax(pred, axis=1)
            if len(numpy.unique(label)) > 2:
                raise MXNetError("F1 currently only supports binary"
                                 " classification.")
            tp = numpy.sum((pred_label == 1) & (label == 1))
            fp = numpy.sum((pred_label == 1) & (label == 0))
            fn = numpy.sum((pred_label == 0) & (label == 1))
            precision = tp / (tp + fp) if tp + fp > 0 else 0.0
            recall = tp / (tp + fn) if tp + fn > 0 else 0.0
            if precision + recall > 0:
                self.sum_metric += 2 * precision * recall / (precision + recall)
            self.num_inst += 1


class MAE(EvalMetric):
    def __init__(self):
        super().__init__("mae")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += numpy.abs(label - pred).mean()
            self.num_inst += 1


class MSE(EvalMetric):
    def __init__(self):
        super().__init__("mse")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1


class RMSE(EvalMetric):
    def __init__(self):
        super().__init__("rmse")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += numpy.sqrt(((label - pred) ** 2.0).mean())
            self.num_inst += 1


class TopKAccuracy(EvalMetric):
    """Top-k classification accuracy: correct if the true label is among
    the k highest-scoring classes (k=1 degenerates to Accuracy)."""

    def __init__(self, top_k=5):
        if top_k < 1:
            raise ValueError("top_k must be >= 1")
        self.top_k = int(top_k)
        super().__init__("top_k_accuracy_%d" % self.top_k)

    def update(self, labels, preds):
        if len(labels) != len(preds):
            raise MXNetError("labels and preds length mismatch")
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label).ravel().astype(numpy.int64)
            if pred.shape[1] <= self.top_k:
                raise MXNetError(
                    "top_k_accuracy_%d is meaningless for %d classes "
                    "(every label is trivially in the top %d) — use a "
                    "smaller top_k" % (self.top_k, pred.shape[1],
                                       self.top_k))
            k = self.top_k
            topk = numpy.argpartition(pred, -k, axis=1)[:, -k:]
            self.sum_metric += int((topk == label[:, None]).any(axis=1)
                                   .sum())
            self.num_inst += label.shape[0]


class CrossEntropy(EvalMetric):
    def __init__(self):
        super().__init__("cross-entropy")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy.arange(label.shape[0]), label.astype(numpy.int64)]
            self.sum_metric += (-numpy.log(numpy.maximum(prob, 1e-30))).sum()
            self.num_inst += label.shape[0]


class Loss(EvalMetric):
    """Mean of the monitored outputs themselves — for loss-emitting
    heads (``SoftmaxCELoss``, MakeLoss-style outputs) whose executor
    output IS the per-example loss, so probability-based metrics don't
    apply. Beyond the reference's metric set (its heads all emit
    predictions), added alongside the fused loss head."""

    def __init__(self):
        super().__init__("loss")

    def update(self, labels, preds):
        for pred in preds:
            pred = _as_numpy(pred)
            self.sum_metric += pred.sum()
            self.num_inst += pred.size


class CustomMetric(EvalMetric):
    """Wrap a feval(label, pred) -> float (reference CustomMetric)."""

    def __init__(self, feval, name=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name)
        self._feval = feval

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            self.sum_metric += self._feval(_as_numpy(label), _as_numpy(pred))
            self.num_inst += 1


def np(numpy_feval, name=None):
    """Create a CustomMetric from a numpy feval (reference metric.np)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name)


def create(metric):
    """Create by name or pass through callables (reference metric.create)."""
    if callable(metric):
        return CustomMetric(metric)
    if isinstance(metric, EvalMetric):
        return metric
    metrics = {"acc": Accuracy, "accuracy": Accuracy, "f1": F1, "mae": MAE,
               "mse": MSE, "rmse": RMSE, "ce": CrossEntropy,
               "cross-entropy": CrossEntropy, "loss": Loss,
               "top_k_accuracy": TopKAccuracy, "top_k_acc": TopKAccuracy,
               "torch": lambda: Torch()}
    try:
        return metrics[metric.lower()]()
    except KeyError:
        raise ValueError("Metric must be either callable or in %s"
                         % sorted(metrics))


def check_label_shapes(labels, preds, shape=0):
    """Check that label/pred collections agree in size (reference
    metric.py:9-19)."""
    if shape == 0:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(
            "Shape of labels {} does not match shape of predictions {}"
            .format(label_shape, pred_shape))


class Torch(EvalMetric):
    """Dummy metric for torch criterions (reference metric.py:188): the
    criterion's forward already IS the loss, so just average it."""

    def __init__(self):
        super().__init__('torch')

    def update(self, _, preds):
        for pred in preds:
            self.sum_metric += float(_as_numpy(pred).mean())
        self.num_inst += 1
