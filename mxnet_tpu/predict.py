"""Deployment predictor: the C predict API, TPU-native.

Parity: ``include/mxnet/c_predict_api.h`` + ``src/c_api/c_predict_api.cc``
— create a predictor from (symbol JSON, parameter bytes), forward only, no
autodiff machinery. The reference strips its engine down to the naive one
under MXNET_PREDICT_ONLY; here the analogue is a single pre-compiled XLA
inference computation with no vjp residuals.

Also covers the amalgamation use case (one self-contained predict path):
``Predictor`` depends only on the core symbol/ndarray modules.
"""
from __future__ import annotations

import io as _io

import numpy as np
import jax

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym_mod
from .parallel.graph import make_graph_fn, integer_semantic_inputs

__all__ = ["Predictor"]


class Predictor:
    """Forward-only executor over a frozen graph.

    Parameters
    ----------
    symbol_json : str — symbol JSON text or a path to it
    param_data : bytes | str | dict — .params file bytes, path, or an
        already-loaded {'arg:name'/'aux:name' -> NDArray} dict
    input_shapes : dict name -> shape
    dev_type/dev_id : accepted for API parity (XLA owns placement)
    output_names : optional list of internal output names — re-heads
        the graph there (reference MXPredCreatePartialOut; feature
        extraction from intermediate layers)
    """

    def __init__(self, symbol_json, param_data, input_shapes,
                 dev_type="cpu", dev_id=0, output_names=None):
        if "{" not in symbol_json:  # path, not JSON text
            with open(symbol_json) as f:
                symbol_json = f.read()
        self._symbol = sym_mod.load_json(symbol_json)
        if output_names:
            # partial-out (reference MXPredCreatePartialOut): re-head the
            # graph at the named internal outputs; bare node names accept
            # the conventional "_output" suffix implicitly
            internals = self._symbol.get_internals()
            avail = internals.list_outputs()
            heads = []
            for key in output_names:
                name = key if key in avail else key + "_output"
                if name not in avail:
                    raise MXNetError(
                        "Predictor: unknown output %r (internals: "
                        "%s...)" % (key, ", ".join(avail[:8])))
                heads.append(internals[name])
            self._symbol = sym_mod.Group(heads)

        if isinstance(param_data, dict):
            save_dict = param_data
        else:
            if isinstance(param_data, (bytes, bytearray)):
                save_dict = nd.load_buffer(bytes(param_data))
            else:
                save_dict = nd.load(param_data)
        arg_params, aux_params = {}, {}
        for k, v in save_dict.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:  # raw name (predict API accepts both layouts)
                arg_params[k] = v

        self._input_shapes = {k: tuple(v) for k, v in input_shapes.items()}
        arg_names = self._symbol.list_arguments()
        aux_names = self._symbol.list_auxiliary_states()
        arg_shapes, out_shapes, aux_shapes = \
            self._symbol.infer_shape(**self._input_shapes)
        if arg_shapes is None:
            raise MXNetError("Predictor: cannot infer shapes")
        self._out_shapes = out_shapes
        self._arg_names = arg_names
        self._params = {}
        for name, shape in zip(arg_names, arg_shapes):
            if name in self._input_shapes:
                continue
            if name in arg_params:
                self._params[name] = arg_params[name]._val
            elif name.endswith("label"):
                # loss-layer labels are dead inputs at inference (the
                # reference predictor likewise binds only data inputs)
                self._params[name] = np.zeros(shape, np.float32)
            else:
                raise MXNetError("Predictor: missing parameter %s" % name)
        self._aux = []
        for name, shape in zip(aux_names, aux_shapes):
            if name not in aux_params:
                raise MXNetError("Predictor: missing aux state %s" % name)
            self._aux.append(aux_params[name]._val)

        graph_fn = make_graph_fn(self._symbol)
        params = self._params
        aux = self._aux

        def run(inputs):
            vals = [params[n] if n in params else inputs[n]
                    for n in arg_names]
            outs, _ = graph_fn(vals, list(aux), False,
                               jax.random.PRNGKey(0))
            return outs

        self._run = jax.jit(run)
        # inputs whose values are INDICES in every use (Embedding data,
        # loss labels): forward keeps their integer dtype — everything
        # else normalizes to the f32 compute dtype as before
        self._integer_inputs = set(integer_semantic_inputs(self._symbol))
        self._outputs = None

    def forward(self, **inputs):
        """Set inputs and run (reference MXPredForward + MXPredSetInput)."""
        arrs = {}
        for k, shape in self._input_shapes.items():
            if k not in inputs:
                raise MXNetError("Predictor.forward: missing input %s" % k)
            v = inputs[k]
            v = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
            if tuple(v.shape) != shape:
                raise MXNetError("input %s: shape %s != bound %s"
                                 % (k, v.shape, shape))
            # INDEX-semantic inputs (token ids into Embedding) keep
            # their integer dtype — a blanket f32 cast corrupts ids
            # above 2^24. Everything else normalizes to the f32
            # compute dtype as it always did, so integer-typed inputs
            # feeding FLOAT graphs (uint8 image batches into a conv
            # net) still work. jit dispatch dtype-keys per input, so
            # mixed-dtype callers compile one program per signature.
            if k in self._integer_inputs and v.dtype.kind in "iub":
                arrs[k] = v
            else:
                arrs[k] = v.astype(np.float32)
        self._outputs = self._run(arrs)
        return self

    def get_output(self, index):
        """Fetch output as numpy (reference MXPredGetOutput)."""
        if self._outputs is None:
            raise MXNetError("call forward first")
        return np.asarray(self._outputs[index])

    @property
    def num_outputs(self):
        return len(self._out_shapes)
