"""Executor: a bound, compiled symbol graph.

Parity: ``/root/reference/python/mxnet/executor.py`` (user API) and
``src/symbol/graph_executor.cc`` (semantics: bind → run; grad_req
write/add/null; aux state mutation; monitor callback).

TPU-first design
----------------
The reference's five-phase bind pipeline (InitGraph/AssignContext/
InitDataEntryInfo/InitDataEntryMemory/InitOpNodes, graph_executor.h:40-69)
exists to schedule per-op kernels and plan memory. Here the *whole graph* is
traced into one XLA computation:

* ``forward(is_train=True)`` runs one jitted program that computes outputs,
  the updated aux states, AND the vjp residuals (the activations autodiff
  needs). The residual pytree of ``jax.vjp`` is flattened inside the traced
  function; its treedef is captured host-side at trace time. This replaces
  the reference's "keep forward buffers alive between Forward and Backward"
  memory plan — residuals are exactly those buffers, chosen by XLA.
* ``backward(head_grads)`` runs a second jitted program: unflatten residuals,
  apply the vjp. Together the pair is the reference's forward/backward node
  split (graph_executor.cc:856-894) with XLA doing memory planning, inplace
  (buffer reuse), and scheduling.
* Gradient aggregation for multi-consumer nodes, grad mirroring
  (MXNET_BACKWARD_DO_MIRROR) and temp-space coloring are all subsumed by
  XLA autodiff + rematerialization + buffer assignment.
"""
from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["Executor"]


def _normalize_dict_or_list(vals, names, what, allow_missing=False):
    if vals is None:
        return [None] * len(names)
    if isinstance(vals, dict):
        out = []
        for n in names:
            if n in vals:
                out.append(vals[n])
            elif allow_missing:
                out.append(None)
            else:
                raise MXNetError("%s: missing entry for %s" % (what, n))
        return out
    vals = list(vals)
    if len(vals) != len(names):
        raise MXNetError("%s: expected %d entries, got %d"
                         % (what, len(names), len(vals)))
    return vals


class Executor:
    """A compiled, bound computation graph."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None, shared_exec=None,
                 _outputs=None):
        self._symbol = symbol
        self._ctx = ctx
        self._group2ctx = group2ctx or {}
        self._monitor_callback = None
        self._monitor_should_run = None

        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        self._arg_names = arg_names
        self._aux_names = aux_names

        self.arg_arrays = _normalize_dict_or_list(args, arg_names, "args")
        if any(a is None for a in self.arg_arrays):
            raise MXNetError("bind: every argument needs an array")
        self.grad_arrays = _normalize_dict_or_list(
            args_grad, arg_names, "args_grad", allow_missing=True)
        self.aux_arrays = _normalize_dict_or_list(
            aux_states, aux_names, "aux_states")
        if any(a is None for a in self.aux_arrays):
            # auto-allocate missing aux (simple_bind path provides them;
            # bind with None aux allocates zeros from inferred shapes)
            shapes = {n: a.shape for n, a in zip(arg_names, self.arg_arrays)}
            _, _, aux_shapes = symbol.infer_shape(**shapes)
            if aux_shapes is None:
                raise MXNetError("bind: cannot infer aux shapes")
            self.aux_arrays = [a if a is not None else nd.zeros(s, ctx)
                               for a, s in zip(self.aux_arrays, aux_shapes)]

        # grad_req -> per-arg list
        if isinstance(grad_req, str):
            reqs = [grad_req] * len(arg_names)
        elif isinstance(grad_req, dict):
            reqs = [grad_req.get(n, "null") for n in arg_names]
        else:
            reqs = list(grad_req)
        self._grad_req = ["null" if g is None else r
                         for r, g in zip(reqs, self.grad_arrays)]

        # output arrays (persistent, refreshed by forward) — shapes from
        # inference over the bound arg shapes
        shapes = {n: a.shape for n, a in zip(arg_names, self.arg_arrays)}
        arg_shapes, out_shapes, _ = symbol.infer_shape(**shapes)
        if out_shapes is None:
            raise MXNetError("bind: cannot infer output shapes from %s"
                             % (shapes,))
        for name, a, s in zip(arg_names, self.arg_arrays, arg_shapes):
            if tuple(a.shape) != tuple(s):
                raise MXNetError("bind: argument %s has shape %s, expected %s"
                                 % (name, a.shape, s))
        if _outputs is not None:
            self._out_arrays = _outputs
        else:
            arg_types = [a.dtype for a in self.arg_arrays]
            _, out_types, _ = symbol.infer_type(*arg_types)
            if out_types is None:
                out_types = [self.arg_arrays[0].dtype] * len(out_shapes)
            self._out_arrays = [nd.empty(s, ctx, dtype=t)
                                for s, t in zip(out_shapes, out_types)]
        self._out_dtypes = [a.dtype for a in self._out_arrays]

        # compiled functions (built lazily; one per is_train mode)
        self._jit_infer = None
        self._jit_train = None
        self._jit_bwd = None
        self._vjp_treedef = None
        self._residuals = None
        self._topo = symbol._topo()
        from .ops.fusion import FusionPlan
        self._fusion_plan = FusionPlan(self._topo, symbol._heads)
        self._jit_monitor = {}
        self._monitor_names = {}
        self._base_key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        self._step = 0

    # ------------------------------------------------------------------
    # graph evaluation (traced under jit)
    def _eval_graph(self, arg_vals, aux_vals, is_train, rng, fuse=True):
        # variables map positionally (list_arguments order = topo order of
        # var nodes); distinct nodes may share a name (reference allows it).
        # The walk + fused-kernel selection live in ops.fusion (the
        # CreateOp-time cuDNN-analogue); monitor runs pass fuse=False so
        # every node's output exists for inspection.
        from .ops.fusion import eval_graph
        return eval_graph(self._topo, self._symbol._heads, arg_vals,
                          aux_vals, is_train, rng,
                          plan=self._fusion_plan if fuse else None)

    # ------------------------------------------------------------------
    def _build_infer(self):
        def run(arg_vals, aux_vals, rng):
            outs, new_aux, _ = self._eval_graph(arg_vals, aux_vals, False, rng)
            return tuple(outs), tuple(new_aux)
        return jax.jit(run)

    def _build_train(self):
        # MXNET_BACKWARD_DO_MIRROR=1 -> gradient mirroring (reference
        # static_graph.cc:400-436) as jax.checkpoint: recompute
        # activations in the backward instead of keeping them
        mirror = os.environ.get("MXNET_BACKWARD_DO_MIRROR", "0") == "1"

        def run(arg_vals, aux_vals, rng):
            def f(av):
                outs, new_aux, _ = self._eval_graph(list(av), aux_vals,
                                                    True, rng)
                return tuple(outs), tuple(new_aux)
            if mirror:
                f = jax.checkpoint(f)
            outs, vjp_fn, new_aux = jax.vjp(f, tuple(arg_vals), has_aux=True)
            leaves, treedef = jax.tree_util.tree_flatten(vjp_fn)
            self._vjp_treedef = treedef  # host capture during trace
            return outs, new_aux, tuple(leaves)
        return jax.jit(run)

    def _build_bwd(self):
        treedef = self._vjp_treedef

        def run(leaves, head_grads):
            vjp_fn = jax.tree_util.tree_unflatten(treedef, list(leaves))
            (arg_grads,) = vjp_fn(tuple(head_grads))
            return arg_grads
        return jax.jit(run)

    # ------------------------------------------------------------------
    # public API (reference executor.py)
    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k not in self._arg_names:
                raise MXNetError("forward: unknown argument %s" % k)
            dst = self.arg_arrays[self._arg_names.index(k)]
            if isinstance(v, NDArray):
                v.copyto(dst)
            else:
                dst[:] = v
        arg_vals = tuple(a._val for a in self.arg_arrays)
        aux_vals = tuple(a._val for a in self.aux_arrays)
        self._step += 1
        rng = jax.random.fold_in(self._base_key, self._step)
        self._last_inputs = (arg_vals, aux_vals, rng)
        if self._monitor_callback is not None and (
                self._monitor_should_run is None or self._monitor_should_run()):
            self._run_monitor(arg_vals, aux_vals, is_train, rng)
        if is_train:
            if self._jit_train is None:
                self._jit_train = self._build_train()
            outs, new_aux, leaves = self._jit_train(arg_vals, aux_vals, rng)
            self._residuals = leaves
        else:
            if self._jit_infer is None:
                self._jit_infer = self._build_infer()
            outs, new_aux = self._jit_infer(arg_vals, aux_vals, rng)
            self._residuals = None
        self._out_dtypes = [v.dtype for v in outs]
        for dst, val in zip(self._out_arrays, outs):
            dst._set(val)
        for dst, val in zip(self.aux_arrays, new_aux):
            dst._set(val)
        return self.outputs

    def backward(self, out_grads=None):
        if self._residuals is None:
            # forward() ran in inference mode (or not at all). The reference
            # permits backward after any forward; recompute the train-mode
            # forward for its residuals (aux updates are discarded so the
            # visible state stays what forward() produced).
            if not hasattr(self, "_last_inputs"):
                raise MXNetError("backward: call forward first")
            if self._jit_train is None:
                self._jit_train = self._build_train()
            arg_vals, aux_vals, rng = self._last_inputs
            _, _, self._residuals = self._jit_train(arg_vals, aux_vals, rng)
        if out_grads is None:
            heads = tuple(jnp.ones(o.shape, dt)
                          for o, dt in zip(self._out_arrays, self._out_dtypes))
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            heads = tuple(
                (g._val if isinstance(g, NDArray) else jnp.asarray(g))
                .astype(dt)
                for g, dt in zip(out_grads, self._out_dtypes))
        if self._jit_bwd is None:
            self._jit_bwd = self._build_bwd()
        arg_grads = self._jit_bwd(self._residuals, heads)
        for g_arr, req, g in zip(self.grad_arrays, self._grad_req, arg_grads):
            if req == "null" or g_arr is None:
                continue
            if req == "add":
                g_arr._set(g_arr._val + g.astype(g_arr.dtype))
            else:  # write
                g_arr._set(g.astype(g_arr.dtype))

    @property
    def outputs(self):
        return self._out_arrays

    @property
    def arg_dict(self):
        return dict(zip(self._arg_names, self.arg_arrays))

    @property
    def grad_dict(self):
        return dict(zip(self._arg_names, self.grad_arrays))

    @property
    def aux_dict(self):
        return dict(zip(self._aux_names, self.aux_arrays))

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """Copy parameter values in (reference executor.py:copy_params_from)."""
        for name, arr in arg_params.items():
            if name in self._arg_names:
                arr.copyto(self.arg_dict[name])
            elif not allow_extra_params:
                raise MXNetError("unknown arg %s" % name)
        if aux_params:
            for name, arr in aux_params.items():
                if name in self._aux_names:
                    arr.copyto(self.aux_dict[name])
                elif not allow_extra_params:
                    raise MXNetError("unknown aux %s" % name)

    # ------------------------------------------------------------------
    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor with new input shapes, sharing memory with
        this one where possible (reference: shape-bucketed executors share
        one memory pool via shared_exec / GraphStoragePool;
        graph_executor.h:48-55). A batch-dim shrink yields views onto this
        executor's buffers, so writes through the new executor are visible
        here — the contract test_executor.test_reshape checks."""
        new_shapes = {n: a.shape for n, a in zip(self._arg_names,
                                                 self.arg_arrays)}
        new_shapes.update(kwargs)
        arg_shapes, out_shapes, _ = self._symbol.infer_shape(**new_shapes)
        if arg_shapes is None:
            raise MXNetError("reshape: cannot infer shapes")

        def make_view(base, shape):
            if base is None:
                return None
            if tuple(base.shape) == tuple(shape):
                return base
            if (base.shape[1:] == tuple(shape[1:])
                    and shape[0] <= base.shape[0]):
                return base.slice(0, shape[0])
            if not allow_up_sizing and np.prod(shape) > base.size:
                raise MXNetError("reshape: %s -> %s grows buffer; pass "
                                 "allow_up_sizing=True" % (base.shape, shape))
            return nd.zeros(shape, self._ctx, dtype=base.dtype)

        new_args = [make_view(a, s) for a, s in zip(self.arg_arrays,
                                                    arg_shapes)]
        new_grads = [make_view(g, s) for g, s in zip(self.grad_arrays,
                                                     arg_shapes)]
        new_outs = [make_view(o, s) for o, s in zip(self._out_arrays,
                                                    out_shapes)]
        return Executor(self._symbol, self._ctx, new_args,
                        new_grads if any(g is not None for g in new_grads)
                        else None,
                        self._grad_req, self.aux_arrays,
                        group2ctx=self._group2ctx, _outputs=new_outs)

    # ------------------------------------------------------------------
    # debugging / monitor (reference: MXExecutorSetMonitorCallback +
    # monitor.py; fires the callback with every node output)
    def set_monitor_callback(self, callback, should_run=None):
        """Install a per-node-output callback. ``should_run`` (optional
        0-arg predicate) gates the expensive eager debug evaluation so a
        Monitor with interval N only pays for sampled batches."""
        self._monitor_callback = callback
        self._monitor_should_run = should_run

    def _run_monitor(self, arg_vals, aux_vals, is_train, rng):
        # ONE compiled debug program returning every node output —
        # cheaper than eager per-op dispatch, though still an extra
        # evaluation per monitored batch (the reference piggybacks on the
        # running executor, graph_executor.cc:803-817; here the fast path
        # is one fused XLA program whose internals aren't addressable, so
        # the debug program is the price of inspection). fuse=False so
        # fused chains report their individual ops' outputs.
        key = bool(is_train)
        if key not in self._jit_monitor:
            def run(av, xv, r, _train=key):
                _, _, env = self._eval_graph(list(av), list(xv), _train,
                                             r, fuse=False)
                names, vals = [], []
                for n in self._topo:
                    if n.is_var:
                        continue
                    for j, out_name in enumerate(n.output_names()):
                        v = env.get((id(n), j))
                        if v is not None:
                            names.append(out_name)
                            vals.append(v)
                self._monitor_names[_train] = names  # host capture @trace
                return tuple(vals)
            self._jit_monitor[key] = jax.jit(run)
        vals = self._jit_monitor[key](tuple(arg_vals), tuple(aux_vals),
                                      rng)
        for name, val in zip(self._monitor_names[key], vals):
            self._monitor_callback(name, nd.array(np.asarray(val)))

    def _compiled_infer(self):
        """The AOT-compiled infer program, cached — debug_str and
        profiler.compiled_stats both read XLA analyses from it without
        paying a recompile per call."""
        cached = getattr(self, "_compiled_infer_cache", None)
        if cached is None:
            arg_vals = [a._val for a in self.arg_arrays]
            aux_vals = [a._val for a in self.aux_arrays]
            if self._jit_infer is None:
                self._jit_infer = self._build_infer()
            cached = self._jit_infer.lower(
                arg_vals, aux_vals, jax.random.PRNGKey(0)).compile()
            self._compiled_infer_cache = cached
        return cached

    def debug_str(self):
        """Execution-plan dump: the graph plus the compiled program's
        buffer plan (reference ``GraphExecutor::Print``,
        graph_executor.cc:821-854, which reports per-node storage and
        'Total N MB'). Here the planner is XLA buffer assignment, so the
        totals come from the jitted forward's memory analysis; the dump is
        per-program (infer path) rather than per-node because XLA fuses
        nodes into one executable."""
        lines = [self._symbol.debug_str()]
        try:
            m = self._plan_memory
        except AttributeError:
            m = None
        try:
            if m is None:
                m = self._compiled_infer().memory_analysis()
                self._plan_memory = m  # compile once; plan is static
            if m is not None:
                mb = 2.0 ** 20
                lines.append(
                    "Compiled plan (XLA buffer assignment):\n"
                    "  argument  %.2f MB\n  output    %.2f MB\n"
                    "  temp      %.2f MB\n  generated code %.2f MB\n"
                    "Total %.2f MB" % (
                        m.argument_size_in_bytes / mb,
                        m.output_size_in_bytes / mb,
                        m.temp_size_in_bytes / mb,
                        m.generated_code_size_in_bytes / mb,
                        (m.argument_size_in_bytes + m.output_size_in_bytes
                         + m.temp_size_in_bytes) / mb))
        except Exception:  # memory analysis is backend-dependent
            pass
        return "\n".join(lines)
