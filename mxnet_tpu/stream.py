"""URI stream IO — the ``dmlc::Stream`` analogue.

The reference routes every checkpoint/data file through
``dmlc::Stream::Create``, which dispatches on the URI scheme to local
files, S3, or HDFS (``/root/reference/make/config.mk:92-100`` compile
flags USE_S3/USE_HDFS; ``src/io/`` uses the same streams). Python-side,
that means ``mx.nd.save("s3://bucket/model.params", ...)`` just works
when the backend is compiled in.

Here ``open_stream`` is that dispatch point: NDArray/Symbol save+load
and the checkpoint helpers call it instead of ``open``. Local paths and
``file://`` open directly; ``s3://`` uses boto3 when importable
(buffered through memory — checkpoint-sized objects); ``hdfs://`` needs
pyarrow. Neither extra dependency ships in this image, so those schemes
raise a loud, actionable ``MXNetError`` instead of silently writing a
local file named "s3:/..." — the failure mode the reference gates with
compile-time USE_S3/USE_HDFS errors.
"""
from __future__ import annotations

import io
import os

from .base import MXNetError

__all__ = ["open_stream", "is_uri"]

_SCHEMES = ("s3://", "hdfs://", "file://")


def is_uri(path):
    return isinstance(path, str) and path.startswith(_SCHEMES)


class _S3Stream(io.BytesIO):
    """Memory-buffered S3 object stream: read pulls the object once,
    write uploads on SUCCESSFUL close (matching dmlc's buffered S3
    writer). Exception safety: the ``with`` form ABORTS the upload when
    the body raises, and a stream dropped to GC aborts too — publishing
    a truncated object that "looks complete" is exactly the corruption
    the local tmp+rename path prevents. A bare ``close()`` call always
    publishes (an explicit call is taken as intent); non-``with`` users
    must call ``abort()`` on their exception paths."""

    def __init__(self, uri, mode):
        try:
            import boto3
        except ImportError:
            raise MXNetError(
                "%s: S3 streams need boto3, which is not installed in "
                "this image (the reference gates this behind USE_S3=1 "
                "at compile time, make/config.mk:100). Install boto3 or "
                "copy to a local path first." % uri)
        self._client = boto3.client("s3")
        rest = uri[len("s3://"):]
        self._bucket, _, self._key = rest.partition("/")
        if not self._bucket or not self._key:
            raise MXNetError("malformed S3 uri: %s" % uri)
        self._writing = "w" in mode
        self._abort = False
        if self._writing:
            super().__init__()
        else:
            body = self._client.get_object(Bucket=self._bucket,
                                           Key=self._key)["Body"].read()
            super().__init__(body)

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            self._abort = True
        self.close()
        return False

    def __del__(self):
        # GC finalization is NOT a successful close: a stream dropped
        # during exception unwind (no ``with`` block) must never publish
        # its partial buffer.
        self._abort = True
        try:
            self.close()
        except Exception:
            pass

    def abort(self):
        """Discard the buffer: a following close() will NOT upload.
        Non-``with`` users should call this from their exception path —
        only the context-manager form aborts automatically."""
        self._abort = True

    def close(self):
        if self._writing and not self.closed and not self._abort:
            self._client.put_object(Bucket=self._bucket, Key=self._key,
                                    Body=self.getvalue())
        super().close()


class _TextStream(io.TextIOWrapper):
    """Text wrapper that propagates abort-on-exception to the S3/HDFS
    buffer underneath."""

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None and hasattr(self.buffer, "_abort"):
            self.buffer._abort = True
        return super().__exit__(exc_type, exc_val, exc_tb)


class _HdfsWriteStream(io.BytesIO):
    """Memory-buffered HDFS write: upload on SUCCESSFUL close only —
    same abort-on-exception contract as _S3Stream, so a failed save
    never publishes a truncated file."""

    def __init__(self, hdfs, path):
        super().__init__()
        self._hdfs = hdfs
        self._path = path
        self._abort = False

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            self._abort = True
        self.close()
        return False

    def __del__(self):
        self._abort = True  # see _S3Stream.__del__
        try:
            self.close()
        except Exception:
            pass

    def abort(self):
        """See _S3Stream.abort."""
        self._abort = True

    def close(self):
        if not self.closed and not self._abort:
            with self._hdfs.open_output_stream(self._path) as out:
                out.write(self.getvalue())
        super().close()


def open_stream(path, mode="rb"):
    """Open ``path`` by URI scheme (the ``dmlc::Stream::Create``
    dispatch). Returns a file-like object usable as a context manager.
    Remote schemes support plain read ("r"/"rb") and whole-object write
    ("w"/"wb") only — append/update modes raise (the reference's
    dmlc::Stream has the same read-or-create contract)."""
    if not isinstance(path, (str, os.PathLike)):
        raise MXNetError("open_stream: path must be str, got %r"
                         % type(path))
    p = os.fspath(path)
    if p.startswith("file://"):
        p = p[len("file://"):]
        return open(p, mode)
    if p.startswith(("s3://", "hdfs://")):
        base = mode.replace("b", "")
        if base not in ("r", "w"):
            raise MXNetError(
                "%s: remote streams support only 'r'/'w' modes, got %r "
                "(append/update need read-modify-write through a local "
                "copy)" % (p, mode))
    if p.startswith("s3://"):
        s = _S3Stream(p, mode)
        if "b" not in mode:
            return _TextStream(s, encoding="utf-8")
        return s
    if p.startswith("hdfs://"):
        try:
            from pyarrow import fs as pafs
        except ImportError:
            raise MXNetError(
                "%s: HDFS streams need pyarrow, which is not installed "
                "in this image (the reference gates this behind "
                "USE_HDFS=1, make/config.mk:92). Copy to a local path "
                "first." % p)
        rest = p.split("://", 1)[1]
        if "/" not in rest:
            raise MXNetError("malformed HDFS uri (no path): %s" % p)
        hdfs = pafs.HadoopFileSystem.from_uri(p)
        rel = "/" + rest.split("/", 1)[1]
        if "w" in mode:
            stream = _HdfsWriteStream(hdfs, rel)
            if "b" not in mode:
                return _TextStream(stream, encoding="utf-8")
            return stream
        stream = hdfs.open_input_stream(rel)
        if "b" not in mode:
            return io.TextIOWrapper(stream, encoding="utf-8")
        return stream
    return open(p, mode)
