"""Custom operators defined in Python.

Parity: ``/root/reference/python/mxnet/operator.py`` — ``PythonOp``/
``NumpyOp`` (synchronous host-side ops, reference ``native_op-inl.h`` C
callback bridge) and ``NDArrayOp`` (async, ``ndarray_op-inl.h``).

TPU-first: the host bridge is ``jax.pure_callback`` — the op participates in
the jitted XLA program, XLA inserts the device↔host transfers around it, and
``jax.custom_vjp`` routes the user's ``backward`` the same way. This is
exactly the role NativeOp's blocking C callback plays in the reference, but
it stays inside the compiled graph instead of breaking the engine pipeline.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .ops.registry import OpSpec
from .symbol import Symbol, _Node, Variable
from .name import NameManager

__all__ = ["PythonOp", "NumpyOp", "NDArrayOp"]


class PythonOp:
    """Base class for Python-defined operators.

    Subclasses override ``forward(in_data, out_data)`` (write outputs into
    out_data in place), ``backward(out_grad, in_data, out_data, in_grad)``,
    ``infer_shape(in_shape) -> (in_shapes, out_shapes)``,
    ``list_arguments``/``list_outputs``. ``need_top_grad=False`` declares a
    loss op whose backward ignores head gradients (reference operator.py:
    NumpyOp(need_top_grad)).
    """

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    # --- user-overridable interface (defaults: identity op, matching the
    # reference operator.py base-class behavior exercised by test_python_op)
    def forward(self, in_data, out_data):
        out_data[0][:] = in_data[0]

    def backward(self, out_grad, in_data, out_data, in_grad):
        if self.need_top_grad_:
            in_grad[0][:] = out_grad[0]
        else:
            in_grad[0][:] = 1.0

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def need_top_grad(self):
        return self.need_top_grad_

    # --- symbol creation ----------------------------------------------
    def __call__(self, *args, **kwargs):
        spec = _PythonOpSpec(self)
        name = kwargs.pop("name", None)
        name = NameManager.current().get(name, type(self).__name__.lower())
        arg_names = self.list_arguments()
        inputs = [None] * len(arg_names)
        for i, s in enumerate(args):
            inputs[i] = s._single_head()
        for k, s in kwargs.items():
            if k not in arg_names:
                raise MXNetError("unknown input %s" % k)
            inputs[arg_names.index(k)] = s._single_head()
        for i, inp in enumerate(inputs):
            if inp is None:
                inputs[i] = Variable(name + "_" + arg_names[i])._single_head()
        node = _Node("_Python_" + type(self).__name__, spec, {}, name, inputs)
        return Symbol([(node, i) for i in range(len(self.list_outputs()))])

    def get_symbol(self, *args, **kwargs):
        return self(*args, **kwargs)


# NumpyOp and NDArrayOp share PythonOp's protocol; the reference's
# distinction (blocking TBlob callback vs async NDArray callback) collapses
# on TPU — both run as pure_callbacks scheduled by XLA.
class NumpyOp(PythonOp):
    pass


class NDArrayOp(PythonOp):
    pass


class _PythonOpSpec(OpSpec):
    """Adapter presenting a PythonOp instance as an OpSpec."""

    def __init__(self, pyop):
        self.pyop = pyop
        self.name = "_Python_" + type(pyop).__name__
        self._out_shapes = None

    def arguments(self, p):
        return self.pyop.list_arguments()

    def outputs(self, p):
        return self.pyop.list_outputs()

    def infer_shape(self, p, in_shapes):
        if any(s is None for s in in_shapes):
            return list(in_shapes), [None] * len(self.pyop.list_outputs()), []
        ins, outs = self.pyop.infer_shape([list(s) for s in in_shapes])
        self._out_shapes = [tuple(o) for o in outs]
        return ([tuple(s) for s in ins], self._out_shapes, [])

    def forward(self, p, ins, aux, is_train, rng):
        pyop = self.pyop
        _, out_shapes = pyop.infer_shape([list(x.shape) for x in ins])
        out_avals = [jax.ShapeDtypeStruct(tuple(s), ins[0].dtype)
                     for s in out_shapes]
        in_avals = [jax.ShapeDtypeStruct(x.shape, x.dtype) for x in ins]

        def host_forward(*in_arrays):
            in_np = [np.asarray(a) for a in in_arrays]
            out_np = [np.zeros(s, dtype=in_np[0].dtype) for s in out_shapes]
            pyop.forward(in_data=in_np, out_data=out_np)
            return tuple(out_np)

        def host_backward(*flat):
            n_out = len(out_shapes)
            n_in = len(in_avals)
            out_grad = [np.asarray(a) for a in flat[:n_out]]
            in_data = [np.asarray(a) for a in flat[n_out:n_out + n_in]]
            out_data = [np.asarray(a) for a in flat[n_out + n_in:]]
            in_grad = [np.zeros_like(a) for a in in_data]
            if not pyop.need_top_grad():
                out_grad = []  # loss op: head grads not materialized (ref)
            pyop.backward(out_grad=out_grad, in_data=in_data,
                          out_data=out_data, in_grad=in_grad)
            return tuple(in_grad)

        @jax.custom_vjp
        def f(*xs):
            return jax.pure_callback(host_forward, tuple(out_avals), *xs)

        def f_fwd(*xs):
            outs = jax.pure_callback(host_forward, tuple(out_avals), *xs)
            return outs, (xs, outs)

        def f_bwd(res, gs):
            xs, outs = res
            if not isinstance(gs, tuple):
                gs = (gs,)
            grads = jax.pure_callback(host_backward, tuple(in_avals),
                                      *(tuple(gs) + tuple(xs) + tuple(outs)))
            return tuple(grads)

        f.defvjp(f_fwd, f_bwd)
        outs = f(*ins)
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        return list(outs), []
