"""NDArray: the imperative array API, rebuilt on JAX/XLA.

Parity target: ``/root/reference/python/mxnet/ndarray.py`` (user API) and
``/root/reference/src/ndarray/ndarray.cc`` + ``include/mxnet/ndarray.h``
(semantics: mutation, zero-copy axis-0 slices and reshapes, asynchronous
execution, binary checkpoint format at ``ndarray.cc:518-640``).

TPU-first design
----------------
The reference queues every op onto a threaded dependency engine and backs
arrays with raw device pointers. On TPU, XLA's runtime *is* the async engine:
each jnp op dispatches asynchronously and ``asnumpy()``/``wait_to_read()``
block on the XLA future — so the whole engine layer (``src/engine/``)
collapses into the PJRT runtime. Mutation and views are preserved on top of
immutable XLA buffers with a storage-chunk indirection:

* ``_Chunk`` owns one flat device buffer (the analogue of
  ``Chunk{Storage::Handle}`` at ``include/mxnet/ndarray.h:269-340``).
* An ``NDArray`` is ``(chunk, shape, offset)`` — exactly the reference's
  view triple (``ndarray.h:227-250``); ``Slice``/``Reshape`` share the chunk.
* Writes replace or ``.at[...].set`` the chunk's buffer, so every view sees
  the write (write-through), while XLA still sees pure functional updates
  (donation makes the common whole-buffer case zero-copy).
"""
from __future__ import annotations

import struct
import sys
import weakref

import numpy as np

from .base import MXNetError, DTYPE_NP_TO_MX, DTYPE_MX_TO_NP, np_dtype
from .context import Context, current_context

import jax
import jax.numpy as jnp

__all__ = ["NDArray", "zeros", "ones", "full", "empty", "array", "save",
           "load", "concatenate", "waitall", "onehot_encode", "clip", "dot",
           "norm", "sqrt", "rsqrt", "square", "abs", "sign", "round", "ceil",
           "floor", "exp", "log", "cos", "sin", "maximum", "minimum",
           "negative",
           "choose_element_0index", "fill_element_0index", "sum", "max",
           "min", "argmax_channel", "transpose", "imdecode"]

# Live chunks, for waitall() — the reference's Engine::WaitForAll
# (include/mxnet/engine.h:172).
_LIVE_CHUNKS: "weakref.WeakSet[_Chunk]" = weakref.WeakSet()


class _Chunk:
    """Storage buffer; the unit of mutation and engine tracking.

    ``buf`` may be stored in ANY shape (only its total size is invariant):
    the whole-array fast path then returns/stores buffers without a reshape
    dispatch — important on TPU where every dispatch pays host↔device RTT.
    View reads/writes flatten on demand.
    """

    __slots__ = ("buf", "ctx", "__weakref__")

    def __init__(self, buf, ctx: Context):
        self.buf = buf  # jax.Array, any shape
        self.ctx = ctx
        _LIVE_CHUNKS.add(self)


def _prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out


def _to_jax(value, dtype=None):
    """Convert scalars/numpy/NDArray to a jax array."""
    if isinstance(value, NDArray):
        value = value._val
    if dtype is not None:
        return jnp.asarray(value, dtype=np.dtype(dtype))
    return jnp.asarray(value)


class NDArray:
    """A possibly-view array with mutation semantics over XLA buffers."""

    __slots__ = ("_chunk", "_shape", "_offset", "writable")

    # make numpy defer to our reflected ops (np_array * ndarray etc.)
    __array_priority__ = 100.0

    def __init__(self, chunk: _Chunk, shape, offset=0, writable=True):
        self._chunk = chunk
        self._shape = tuple(int(s) for s in shape)
        self._offset = int(offset)
        self.writable = writable

    # ------------------------------------------------------------------
    # construction helpers
    @staticmethod
    def _new_alloc(shape, ctx=None, dtype=np.float32):
        ctx = ctx or current_context()
        dt = np_dtype(dtype)
        buf = jnp.zeros((_prod(shape),), dtype=dt)
        buf = jax.device_put(buf, ctx.jax_device())
        return NDArray(_Chunk(buf, ctx), shape)

    @staticmethod
    def _from_jax(val, ctx=None):
        ctx = ctx or current_context()
        shape = val.shape if val.ndim else (1,)
        return NDArray(_Chunk(val, ctx), shape)

    # ------------------------------------------------------------------
    # storage access
    @property
    def _size(self):
        return _prod(self._shape)

    @property
    def _is_whole(self):
        return self._offset == 0 and self._size == self._chunk.buf.size

    @property
    def _val(self):
        """Read this (view of the) chunk as a shaped jax array."""
        buf = self._chunk.buf
        if self._is_whole:
            return buf if buf.shape == self._shape else buf.reshape(self._shape)
        flat = buf.reshape(-1)
        return jax.lax.dynamic_slice(flat, (self._offset,),
                                     (self._size,)).reshape(self._shape)

    def _set(self, value):
        """Write a shaped jax array into this view (write-through)."""
        if not self.writable:
            raise MXNetError("trying to write to a read-only NDArray")
        value = jnp.asarray(value)
        if value.shape != self._shape:
            value = jnp.broadcast_to(value, self._shape)
        value = value.astype(self.dtype)
        # keep the chunk pinned to its device (multi-chip copies route
        # through here like the reference's CopyFromTo cross-dev kernels)
        try:
            if value.device != self._chunk.buf.device:
                value = jax.device_put(value, self._chunk.buf.device)
        except AttributeError:
            pass  # sharded arrays: placement handled by sharding
        if self._is_whole:
            self._chunk.buf = value  # keep natural shape; readers adapt
        else:
            self._chunk.buf = jax.lax.dynamic_update_slice(
                self._chunk.buf.reshape(-1), value.reshape(-1),
                (self._offset,))
        return self

    # ------------------------------------------------------------------
    # basic properties
    @property
    def shape(self):
        return self._shape

    @property
    def size(self):
        return self._size

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dtype(self):
        return np.dtype(self._chunk.buf.dtype)

    @property
    def context(self):
        return self._chunk.ctx

    ctx = context

    def __repr__(self):
        return "<%s %s @%s>" % (type(self).__name__,
                                "x".join(str(s) for s in self._shape),
                                self.context)

    # ------------------------------------------------------------------
    # synchronization (engine parity)
    def wait_to_read(self):
        """Block until pending writes complete (``NDArray::WaitToRead``)."""
        jax.block_until_ready(self._chunk.buf)

    def wait_to_write(self):
        jax.block_until_ready(self._chunk.buf)

    # ------------------------------------------------------------------
    # host interop
    def asnumpy(self):
        """Copy to a numpy array, blocking (``MXNDArraySyncCopyToCPU``)."""
        out = np.asarray(jax.device_get(self._val)).astype(self.dtype, copy=False)
        if not out.flags.writeable:
            out = out.copy()
        return out

    def asscalar(self):
        if self._size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def astype(self, dtype):
        res = empty(self._shape, ctx=self.context, dtype=dtype)
        res._set(self._val.astype(np_dtype(dtype)))
        return res

    # ------------------------------------------------------------------
    # views (zero-copy in the reference: ndarray.h:227-250)
    def reshape(self, new_shape):
        # MXNet has no 0-dim arrays: scalars are shape (1,) (ndarray.py ref).
        new_shape = tuple(int(s) for s in new_shape) or (1,)
        if _prod(new_shape) != self._size:
            raise MXNetError("NDArray.reshape: size must not change")
        return NDArray(self._chunk, new_shape, self._offset, self.writable)

    def slice(self, start, stop):
        start, stop = int(start), int(stop)
        if not (0 <= start <= stop <= self._shape[0]):
            raise MXNetError("slice out of range")
        stride = self._size // self._shape[0] if self._shape[0] else 0
        return NDArray(self._chunk, (stop - start,) + self._shape[1:],
                       self._offset + start * stride, self.writable)

    def __getitem__(self, in_slice):
        if isinstance(in_slice, int):
            return self.slice(in_slice, in_slice + 1).reshape(self._shape[1:] or (1,))
        if isinstance(in_slice, slice):
            if in_slice.step is not None and in_slice.step != 1:
                raise MXNetError("NDArray only supports contiguous slicing on axis 0")
            start = 0 if in_slice.start is None else in_slice.start
            stop = self._shape[0] if in_slice.stop is None else in_slice.stop
            return self.slice(start, stop)
        raise MXNetError("NDArray only supports int/slice indexing on axis 0")

    def __setitem__(self, in_slice, value):
        if isinstance(in_slice, slice) and (in_slice.step is None or in_slice.step == 1):
            target = self if (in_slice.start is None and in_slice.stop is None) \
                else self.__getitem__(in_slice)
        elif isinstance(in_slice, int):
            target = self.__getitem__(in_slice)
        else:
            raise MXNetError("NDArray only supports contiguous slice assignment")
        if isinstance(value, (int, float, np.number)):
            target._set(jnp.full(target._shape, value, dtype=target.dtype))
        else:
            target._set(_to_jax(value, target.dtype))

    # ------------------------------------------------------------------
    # copies
    def copy(self):
        return self.copyto(self.context)

    def copyto(self, other):
        """Copy into another NDArray (mutating it) or to a new one on ctx."""
        if isinstance(other, NDArray):
            if other is self or (other._chunk is self._chunk
                                 and other._offset == self._offset):
                import warnings
                warnings.warn("copy an array to itself, is it intended?")
                return other
            if other.shape != self.shape:
                raise MXNetError("copyto shape mismatch %s vs %s"
                                 % (self.shape, other.shape))
            other._set(self._val.astype(other.dtype))
            return other
        if isinstance(other, Context):
            res = empty(self._shape, ctx=other, dtype=self.dtype)
            res._chunk.buf = jax.device_put(self._val.reshape(-1), other.jax_device())
            return res
        raise MXNetError("copyto does not support type " + str(type(other)))

    def as_in_context(self, context):
        if self.context == context:
            return self
        return self.copyto(context)

    # ------------------------------------------------------------------
    # arithmetic — all eager jnp ops; output dtype follows the inputs'
    # common dtype like mshadow (not numpy's int→float64 promotion).
    def _binary(self, other, fn, reverse=False):
        a = self._val
        if isinstance(other, NDArray):
            b = other._val
            rdtype = np.promote_types(self.dtype, other.dtype)
        elif isinstance(other, (int, float, bool, np.number)):
            b = other
            rdtype = self.dtype
        else:
            b = jnp.asarray(other)
            rdtype = np.promote_types(self.dtype, b.dtype)
        out = fn(b, a) if reverse else fn(a, b)
        return NDArray._from_jax(out.astype(rdtype), self.context)

    def __add__(self, o):
        return self._binary(o, jnp.add)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, jnp.subtract)

    def __rsub__(self, o):
        return self._binary(o, jnp.subtract, reverse=True)

    def __mul__(self, o):
        return self._binary(o, jnp.multiply)

    __rmul__ = __mul__

    def __div__(self, o):
        return self._binary(o, jnp.divide)

    def __rdiv__(self, o):
        return self._binary(o, jnp.divide, reverse=True)

    __truediv__ = __div__
    __rtruediv__ = __rdiv__

    def __pow__(self, o):
        return self._binary(o, jnp.power)

    def __neg__(self):
        return NDArray._from_jax(-self._val, self.context)

    # in-place ops mutate the chunk (engine write dependency in the ref)
    def _inplace(self, other, fn):
        b = other._val if isinstance(other, NDArray) else other
        return self._set(fn(self._val, b))

    def __iadd__(self, o):
        return self._inplace(o, jnp.add)

    def __isub__(self, o):
        return self._inplace(o, jnp.subtract)

    def __imul__(self, o):
        return self._inplace(o, jnp.multiply)

    def __idiv__(self, o):
        return self._inplace(o, jnp.divide)

    __itruediv__ = __idiv__

    # pickle support (reference: ndarray.py __getstate__/__setstate__)
    def __reduce__(self):
        return (_ndarray_from_numpy, (self.asnumpy(), self.writable))

    @property
    def T(self):
        return transpose(self)


def _ndarray_from_numpy(data, writable=True):
    arr = array(data)
    arr.writable = writable
    return arr


# ----------------------------------------------------------------------
# creation functions (reference: python/mxnet/ndarray.py empty/zeros/ones/
# array + registered C functions ndarray.cc:664-810)

def empty(shape, ctx=None, dtype=np.float32):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray._new_alloc(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype=np.float32):
    return empty(shape, ctx, dtype)


def _from_device_put(values, shape, ctx):
    ctx = ctx or current_context()
    buf = jax.device_put(values, ctx.jax_device())
    return NDArray(_Chunk(buf, ctx), shape)


def ones(shape, ctx=None, dtype=np.float32):
    if isinstance(shape, int):
        shape = (shape,)
    return _from_device_put(jnp.ones((_prod(shape),), dtype=np_dtype(dtype)),
                            shape, ctx)


def full(shape, val, ctx=None, dtype=np.float32):
    if isinstance(shape, int):
        shape = (shape,)
    return _from_device_put(jnp.full((_prod(shape),), val, dtype=np_dtype(dtype)),
                            shape, ctx)


def array(source_array, ctx=None, dtype=None):
    """Create an NDArray from any array-like (reference ndarray.py:370)."""
    if isinstance(source_array, NDArray):
        src = source_array.asnumpy()
    else:
        src = np.asarray(source_array)
    if dtype is None:
        dtype = src.dtype if src.dtype in DTYPE_NP_TO_MX else np.float32
    src = np.ascontiguousarray(src, dtype=np_dtype(dtype))
    if src.ndim == 0:
        src = src.reshape(1)
    return _from_device_put(src.reshape(-1), src.shape, ctx)


def concatenate(arrays, axis=0, always_copy=True):
    if not arrays:
        raise MXNetError("need at least one array")
    if len(arrays) == 1 and not always_copy and axis == 0:
        return arrays[0]
    val = jnp.concatenate([a._val for a in arrays], axis=axis)
    return NDArray._from_jax(val, arrays[0].context)


def waitall():
    """Block until all queued work completes (``MXNDArrayWaitAll``)."""
    for chunk in list(_LIVE_CHUNKS):
        jax.block_until_ready(chunk.buf)


# ----------------------------------------------------------------------
# registered functions — out= supported like the C registry's mutate_vars

def _maybe_out(val, out, ctx):
    if out is not None:
        out._set(val.astype(out.dtype))
        return out
    return NDArray._from_jax(val, ctx)


def _unary_factory(fn, name):
    def func(arr, out=None):
        return _maybe_out(fn(arr._val).astype(arr.dtype), out, arr.context)
    func.__name__ = name
    func.__doc__ = "Elementwise %s (reference: unary_function-inl.h:146-189)" % name
    return func


sqrt = _unary_factory(jnp.sqrt, "sqrt")
rsqrt = _unary_factory(lambda x: 1.0 / jnp.sqrt(x), "rsqrt")
square = _unary_factory(jnp.square, "square")
exp = _unary_factory(jnp.exp, "exp")
log = _unary_factory(jnp.log, "log")
sign = _unary_factory(jnp.sign, "sign")
cos = _unary_factory(jnp.cos, "cos")
sin = _unary_factory(jnp.sin, "sin")
ceil = _unary_factory(jnp.ceil, "ceil")
floor = _unary_factory(jnp.floor, "floor")
round = _unary_factory(jnp.round, "round")
abs = _unary_factory(jnp.abs, "abs")


def negative(arr, out=None):
    return _maybe_out(-arr._val, out, arr.context)


def maximum(lhs, rhs, out=None):
    a = lhs._val if isinstance(lhs, NDArray) else lhs
    b = rhs._val if isinstance(rhs, NDArray) else rhs
    ctx = lhs.context if isinstance(lhs, NDArray) else rhs.context
    return _maybe_out(jnp.maximum(a, b), out, ctx)


def minimum(lhs, rhs, out=None):
    a = lhs._val if isinstance(lhs, NDArray) else lhs
    b = rhs._val if isinstance(rhs, NDArray) else rhs
    ctx = lhs.context if isinstance(lhs, NDArray) else rhs.context
    return _maybe_out(jnp.minimum(a, b), out, ctx)


def clip(arr, a_min, a_max, out=None):
    """Clip values (reference: ndarray.cc:793 ``clip``)."""
    return _maybe_out(jnp.clip(arr._val, a_min, a_max), out, arr.context)


def dot(lhs, rhs, out=None):
    """Matrix/vector product (reference: ndarray.cc:741 ``dot``)."""
    return _maybe_out(jnp.dot(lhs._val, rhs._val), out, lhs.context)


def norm(arr, out=None):
    """L2 norm, returned as a 1-element NDArray (reference mx.nd.norm)."""
    val = jnp.linalg.norm(arr._val.astype(np.float32).reshape(-1))
    return _maybe_out(val.reshape(1), out, arr.context)


def sum(arr, out=None):
    return _maybe_out(jnp.sum(arr._val).reshape(1), out, arr.context)


def max(arr, out=None):
    return _maybe_out(jnp.max(arr._val).reshape(1), out, arr.context)


def min(arr, out=None):
    return _maybe_out(jnp.min(arr._val).reshape(1), out, arr.context)


def transpose(arr, axes=None, out=None):
    return _maybe_out(jnp.transpose(arr._val, axes), out, arr.context)


def argmax_channel(arr, out=None):
    val = jnp.argmax(arr._val, axis=1).astype(arr.dtype)
    return _maybe_out(val, out, arr.context)


def onehot_encode(indices, out):
    """Fill ``out`` with one-hot rows (reference: ndarray.cc:764)."""
    depth = out.shape[1]
    idx = indices._val.astype(np.int32).reshape(-1)
    val = jax.nn.one_hot(idx, depth, dtype=out.dtype)
    out._set(val)
    return out


def choose_element_0index(lhs, rhs, out=None):
    """out[i] = lhs[i, rhs[i]] (reference: ndarray.cc:771)."""
    idx = rhs._val.astype(np.int32).reshape(-1)
    val = jnp.take_along_axis(lhs._val, idx[:, None], axis=1)[:, 0]
    return _maybe_out(val, out, lhs.context)


def fill_element_0index(lhs, mhs, rhs, out=None):
    """out = lhs; out[i, rhs[i]] = mhs[i] (reference: ndarray.cc:778)."""
    idx = rhs._val.astype(np.int32).reshape(-1)
    rows = jnp.arange(idx.shape[0])
    val = lhs._val.at[rows, idx].set(mhs._val.reshape(-1).astype(lhs.dtype))
    return _maybe_out(val, out, lhs.context)


def imdecode(str_img, clip_rect=(0, 0, 0, 0), out=None, index=0, channels=3, mean=None):
    """Decode an image bytestring (reference: ndarray.cc:799 ``_imdecode``).

    Uses Pillow/OpenCV if available; raises otherwise.
    """
    import io as _io
    try:
        from PIL import Image
        img = np.asarray(Image.open(_io.BytesIO(str_img)).convert("RGB"))
    except ImportError:
        try:
            import cv2
            img = cv2.imdecode(np.frombuffer(str_img, np.uint8), cv2.IMREAD_COLOR)
            img = img[:, :, ::-1]
        except ImportError as exc:
            raise MXNetError("imdecode needs PIL or cv2") from exc
    x0, y0, x1, y1 = clip_rect
    if x1 > 0 or y1 > 0:
        img = img[y0:y1, x0:x1]
    img = np.transpose(img, (2, 0, 1)).astype(np.float32)
    if mean is not None:
        img = img - mean.asnumpy()
    img = img[None]
    if out is not None:
        out._set(jnp.asarray(img))
        return out
    return array(img)


# ----------------------------------------------------------------------
# serialization — bit-compatible with the reference checkpoint format
# (ndarray.cc:518-640: TShape{uint32 ndim, uint32[ndim]}, Context{int32
# dev_type, int32 dev_id}, int32 type_flag, raw data; list files prepend
# uint64 magic 0x112 + uint64 reserved, then dmlc-serialized vectors).

_LIST_MAGIC = 0x112


def _save_one(fo, arr):
    """arr: NDArray or numpy array (host snapshots write without a
    device round-trip)."""
    shape = tuple(arr.shape) or (1,)  # no 0-dim arrays on disk
    fo.write(struct.pack("<I", len(shape)))
    fo.write(struct.pack("<%dI" % len(shape), *shape))
    fo.write(struct.pack("<ii", 1, 0))  # saved as CPU context like the ref
    type_flag = DTYPE_NP_TO_MX[np.dtype(arr.dtype)]
    fo.write(struct.pack("<i", type_flag))
    host = arr.asnumpy() if isinstance(arr, NDArray) else np.asarray(arr)
    data = np.ascontiguousarray(host)
    if sys.byteorder != "little":  # pragma: no cover
        data = data.byteswap()
    fo.write(data.tobytes())


def _load_one(fi) -> NDArray:
    (ndim,) = struct.unpack("<I", fi.read(4))
    if ndim == 0:
        return empty((1,))
    shape = struct.unpack("<%dI" % ndim, fi.read(4 * ndim))
    struct.unpack("<ii", fi.read(8))  # context, ignored: we re-place
    (type_flag,) = struct.unpack("<i", fi.read(4))
    dtype = DTYPE_MX_TO_NP[type_flag]
    count = _prod(shape)
    data = np.frombuffer(fi.read(count * dtype.itemsize), dtype=dtype).reshape(shape)
    return array(data, dtype=dtype)


def save(fname, data):
    """Save a list or str-keyed dict of NDArrays (reference
    ndarray.py:565). numpy arrays are also accepted (host snapshots,
    e.g. the async checkpoint writer, skip the device round-trip)."""
    if isinstance(data, (NDArray, np.ndarray)):
        data = [data]
    names = []
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        arrays = list(data)
    if any(not isinstance(a, (NDArray, np.ndarray)) for a in arrays):
        raise MXNetError("save only accepts NDArrays or numpy arrays")
    from .stream import open_stream  # URI dispatch (dmlc::Stream)
    with open_stream(fname, "wb") as fo:
        fo.write(struct.pack("<QQ", _LIST_MAGIC, 0))
        fo.write(struct.pack("<Q", len(arrays)))
        for arr in arrays:
            _save_one(fo, arr)
        fo.write(struct.pack("<Q", len(names)))
        for name in names:
            enc = name.encode("utf-8")
            fo.write(struct.pack("<Q", len(enc)))
            fo.write(enc)


def _load_stream(fi):
    magic, _ = struct.unpack("<QQ", fi.read(16))
    if magic != _LIST_MAGIC:
        raise MXNetError("Invalid NDArray file format")
    (count,) = struct.unpack("<Q", fi.read(8))
    arrays = [_load_one(fi) for _ in range(count)]
    (nkeys,) = struct.unpack("<Q", fi.read(8))
    if nkeys == 0:
        return arrays
    names = []
    for _ in range(nkeys):
        (ln,) = struct.unpack("<Q", fi.read(8))
        names.append(fi.read(ln).decode("utf-8"))
    return dict(zip(names, arrays))


def load(fname):
    """Load a list or dict saved by :func:`save` (or the reference).
    ``fname`` may be a URI (``s3://``, ``hdfs://``, ``file://``) — the
    reference's dmlc::Stream checkpoint surface."""
    from .stream import open_stream
    with open_stream(fname, "rb") as fi:
        return _load_stream(fi)


def load_buffer(data):
    """Load from in-memory .params bytes (reference
    MXNDArrayLoadFromBuffer / predict API param bytes)."""
    import io as _io
    return _load_stream(_io.BytesIO(data))
