"""Device context.

Parity with ``/root/reference/python/mxnet/context.py`` (Context stack,
``mx.cpu()``/``mx.gpu()``) and ``include/mxnet/base.h:90-175`` (dev type
codes), extended with a first-class TPU device type per the north star.

On this runtime every context resolves to a JAX device: ``tpu(i)`` (and
``gpu(i)``, kept as a compatibility alias for accelerator #i) map to the
default JAX backend's devices; ``cpu()`` maps to the host platform. Data
placement is done with ``jax.device_put`` instead of cudaMemcpy.
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["Context", "current_context", "cpu", "gpu", "tpu", "cpu_pinned"]


class Context:
    """A device context (device type + device id).

    Reference: ``include/mxnet/base.h:90-175`` — kCPU=1, kGPU=2, kCPUPinned=3;
    this build adds kTPU=4 (``Context::kMaxDevType`` in the reference is 4, so
    the on-disk code stays in range).
    """

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}
    default_ctx = None  # set below

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        elif isinstance(device_type, str):
            if device_type not in Context.devstr2type:
                raise MXNetError("unknown device type %s" % device_type)
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        else:
            self.device_typeid = int(device_type)
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self):
        return Context.devtype2str[self.device_typeid]

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_typeid == other.device_typeid
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __repr__ = __str__

    def __enter__(self):
        self._old_ctx = Context.default_ctx
        Context.default_ctx = self
        return self

    def __exit__(self, ptype, value, trace):
        Context.default_ctx = self._old_ctx

    # --- JAX resolution -------------------------------------------------
    def jax_device(self):
        """Resolve this context to a concrete jax.Device.

        tpu/gpu → i-th device of the default (accelerator) backend; falls
        back to host devices when no accelerator is present so code written
        for ``mx.tpu()`` runs unchanged on CPU test meshes.
        cpu/cpu_pinned → i-th host-platform device.
        """
        import jax

        # local_devices, not devices: in multi-process runs the global list
        # leads with other processes' (non-addressable) devices; a Context
        # always names a device THIS process can allocate on (the
        # reference's Context is likewise process-local, base.h:90-175)
        if self.device_type in ("tpu", "gpu"):
            devs = jax.local_devices()
        else:
            try:
                devs = [d for d in jax.local_devices()
                        if d.platform == "cpu"]
                if not devs:
                    raise RuntimeError
            except RuntimeError:
                devs = jax.local_devices()
        if self.device_id < len(devs):
            return devs[self.device_id]
        # Out-of-range ids resolve to device 0 rather than erroring: tests
        # use fake multi-device contexts on a single-device host (reference
        # behavior: allocation fails only when touched).
        return devs[0]


Context.default_ctx = Context("cpu", 0)


def cpu(device_id=0):
    """Return a CPU context (reference: ``context.py:79``)."""
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    """Pinned-memory CPU context; on TPU hosts identical to cpu()."""
    return Context("cpu_pinned", device_id)


def gpu(device_id=0):
    """Accelerator context — compatibility alias mapping onto TPU chips."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    """Return a TPU context: the i-th chip of the default JAX backend."""
    return Context("tpu", device_id)


def current_context():
    """Return the current context (reference: ``context.py:103``)."""
    return Context.default_ctx
