"""Training callbacks.

Parity: ``/root/reference/python/mxnet/callback.py`` — do_checkpoint,
Speedometer (samples/sec logging), ProgressBar, log_train_metric.
"""
from __future__ import annotations

import logging
import math
import time

from . import telemetry as _telemetry

__all__ = ["do_checkpoint", "log_train_metric", "Speedometer", "ProgressBar"]

_TM_SAMPLES_PER_SEC = _telemetry.gauge("train.samples_per_sec")


def do_checkpoint(prefix, async_write=False):
    """Epoch-end checkpoint callback (reference callback.py:11).

    ``async_write=True`` snapshots params to host then writes the file
    on a background thread, so epoch N+1's compute overlaps epoch N's
    checkpoint IO — the cross-step overlap the reference's engine gave
    its async ops (SURVEY §7 hard part (e)). The previous write is
    joined before starting the next, so at most one writer runs and
    files complete in order.
    """
    state = {"thread": None, "error": None}

    def _write(args):
        from .model import save_checkpoint
        try:
            save_checkpoint(prefix, *args)
        except BaseException as e:  # surfaced at the next join
            state["error"] = e

    def _join():
        if state["thread"] is not None:
            state["thread"].join()
            state["thread"] = None
        if state["error"] is not None:
            err, state["error"] = state["error"], None
            raise err

    def _callback(iter_no, sym, arg, aux):
        if not async_write:
            from .model import save_checkpoint
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
            return
        import threading
        _join()
        # snapshot to HOST numpy on the caller's thread (values may be
        # mutated by the next epoch; nd.save accepts numpy, so the
        # writer never touches the device); file IO overlaps compute
        arg_snap = {k: v.asnumpy() for k, v in arg.items()}
        aux_snap = {k: v.asnumpy() for k, v in aux.items()}
        t = threading.Thread(
            target=_write, args=((iter_no + 1, sym, arg_snap, aux_snap),),
            daemon=True)
        t.start()
        state["thread"] = t

    _callback.finalize = _join
    return _callback


def log_train_metric(period):
    """Log evaluation metric every `period` batches (reference :30)."""
    def _callback(param):
        if param.nbatch % period == 0:
            name, value = param.eval_metric.get()
            logging.info("Iter[%d] Batch [%d]\tTrain-%s=%f",
                         param.epoch, param.nbatch, name, value)
    return _callback


class Speedometer:
    """Log training speed every `frequent` batches (reference :49).

    A batch count lower than the previous call means a new epoch
    started; the timer re-arms rather than reporting a bogus speed
    across the epoch boundary.

    Timing uses ``time.perf_counter()`` — ``time.time()`` is wall
    clock, which can jump (NTP slew/step) and report negative or
    wildly wrong speeds. A zero elapsed interval (coarse clocks, or a
    callback invoked twice for one batch) skips the report instead of
    raising ``ZeroDivisionError``. The measured rate is also published
    as the ``train.samples_per_sec`` telemetry gauge
    (doc/observability.md) whenever telemetry is enabled.
    """

    def __init__(self, batch_size, frequent=50):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0

    def _rearm(self):
        self.init = True
        self.tic = time.perf_counter()

    def __call__(self, param):
        count = param.nbatch
        if count < self.last_count:
            self.init = False
        self.last_count = count
        if not self.init:
            self._rearm()
            return
        if count % self.frequent:
            return
        elapsed = time.perf_counter() - self.tic
        if elapsed <= 0:
            self._rearm()
            return
        speed = self.frequent * self.batch_size / elapsed
        _TM_SAMPLES_PER_SEC.set(speed)
        if param.eval_metric is None:
            logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                         param.epoch, count, speed)
        else:
            name, value = param.eval_metric.get()
            logging.info("Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                         "\tTrain-%s=%f",
                         param.epoch, count, speed, name, value)
        self._rearm()


class ProgressBar:
    """Text progress bar per epoch (reference :92).

    ``total=0`` (an empty epoch — e.g. a discard-tail iterator whose
    data is smaller than one batch) draws a full bar instead of
    dividing by zero, and an overrun count (epoch_size semantics can
    serve more batches than ``total`` predicted) clamps the bar at
    ``bar_len`` characters while the percentage keeps counting."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        frac = 1.0 if self.total <= 0 else count / float(self.total)
        filled_len = min(self.bar_len, int(round(self.bar_len * frac)))
        percents = math.ceil(100.0 * frac)
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")
