"""Data-parallel executor management.

Parity: ``/root/reference/python/mxnet/executor_manager.py`` —
``_split_input_slice`` work-load slicing, parameter name checking,
``DataParallelExecutorGroup`` (one executor per device, batch sliced
across them) and ``DataParallelExecutorManager`` (+ bucketing support).

TPU-first note: on a TPU pod the fused pjit trainer
(``mxnet_tpu/parallel``) supersedes this host-side slicing — XLA shards
the batch over the mesh and inserts psum. This module keeps the reference
execution model for API parity and for heterogeneous `ctx` lists on one
process; slices run as separate XLA dispatches that the runtime pipelines.
"""
from __future__ import annotations

import logging

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .context import cpu

__all__ = ["DataParallelExecutorManager", "DataParallelExecutorGroup",
           "_split_input_slice", "_check_arguments", "_load_data",
           "_load_label"]


def _split_input_slice(batch_size, work_load_list):
    """Split batch into per-device slices proportional to work load
    (reference executor_manager.py:11-43 semantics): per-device counts
    are the rounded proportional shares, any rounding shortfall lands on
    the last device, and boundaries are clamped to the batch."""
    total = sum(work_load_list)
    counts = [round(w * batch_size / total) for w in work_load_list]
    if sum(counts) < batch_size:
        counts[-1] += batch_size - sum(counts)
    bounds = [0]
    for c in counts:
        bounds.append(int(min(bounds[-1] + c, batch_size)))
    if any(lo >= hi for lo, hi in zip(bounds, bounds[1:])):
        raise ValueError("Too many slices such that some splits are empty")
    return [slice(lo, hi) for lo, hi in zip(bounds, bounds[1:])]


def _check_arguments(symbol):
    """Reject duplicated argument/aux names (reference :46-73)."""
    arg_set = set()
    for name in symbol.list_arguments():
        if name in arg_set:
            raise ValueError("Find duplicated argument name \"%s\"" % name)
        arg_set.add(name)
    aux_set = set()
    for name in symbol.list_auxiliary_states():
        if name in aux_set:
            raise ValueError("Find duplicated auxiliary param name \"%s\""
                             % name)
        aux_set.add(name)


def _load_general(data, targets):
    """Load a batch's arrays into per-device target slices (:76-86)."""
    for d_src, d_targets in zip(data, targets):
        for slice_idx, d_dst in d_targets:
            if d_src.shape == d_dst.shape:
                d_src.copyto(d_dst)
            else:
                d_src[slice_idx].copyto(d_dst)


def _load_data(batch, targets):
    _load_general(batch.data, targets)


def _load_label(batch, targets):
    _load_general(batch.label, targets)


class DataParallelExecutorGroup:
    """One executor per device over sliced batches (reference :146-228)."""

    def __init__(self, sym, arg_names, param_names, ctx, slices, train_data,
                 shared_group=None):
        _check_arguments(sym)
        self.arg_names = arg_names
        self.param_names = param_names
        data_shapes = dict(train_data.provide_data + train_data.provide_label)

        self.train_execs = []
        for i, ctxi in enumerate(ctx):
            shapes = {}
            for k, v in data_shapes.items():
                shapes[k] = (slices[i].stop - slices[i].start,) + tuple(v[1:])
            arg_shapes, _, aux_shapes = sym.infer_shape(**shapes)
            if arg_shapes is None:
                raise MXNetError("cannot infer shapes for executor group")
            grad_req = {name: ("write" if name in param_names else "null")
                        for name in arg_names}
            if shared_group is None:
                exec_args = [nd.zeros(s, ctxi) for s in arg_shapes]
            else:
                base = shared_group.train_execs[i]
                exec_args = []
                for name, s in zip(arg_names, arg_shapes):
                    if name in param_names:
                        exec_args.append(base.arg_dict[name])
                    else:
                        exec_args.append(nd.zeros(s, ctxi))
            grads = {name: nd.zeros(s, ctxi)
                     for name, s in zip(arg_names, arg_shapes)
                     if name in param_names}
            train_exec = sym.bind(ctxi, exec_args, grads, grad_req)
            self.train_execs.append(train_exec)

        self.data_names = [x[0] for x in train_data.provide_data]
        self.label_names = [x[0] for x in train_data.provide_label]
        self.data_arrays = [
            [(slices[i], e.arg_dict[name])
             for i, e in enumerate(self.train_execs)]
            for name in self.data_names]
        self.label_arrays = [
            [(slices[i], e.arg_dict[name])
             for i, e in enumerate(self.train_execs)]
            for name in self.label_names]
        self.param_idx = [i for i, name in enumerate(arg_names)
                          if name in param_names]
        self.param_names = [arg_names[i] for i in self.param_idx]
        self.param_arrays = [[e.arg_arrays[i] for e in self.train_execs]
                             for i in self.param_idx]
        self.grad_arrays = [[e.grad_arrays[i] for e in self.train_execs]
                            for i in self.param_idx]
        self.aux_arrays = [[e.aux_arrays[i] for e in self.train_execs]
                           for i in range(len(sym.list_auxiliary_states()))]
        self.slices = slices

    def load_data_batch(self, data_batch):
        _load_data(data_batch, self.data_arrays)
        _load_label(data_batch, self.label_arrays)

    def forward(self, is_train=False):
        for ex in self.train_execs:
            ex.forward(is_train=is_train)

    def backward(self):
        for ex in self.train_execs:
            ex.backward()

    def update_metric(self, metric, labels):
        for ex, part in zip(self.train_execs, self.slices):
            metric.update([lbl[part] for lbl in labels], ex.outputs)


class DataParallelExecutorManager:
    """Manage executor groups incl. bucketing (reference :230-372)."""

    def __init__(self, symbol, ctx, train_data, arg_names, param_names,
                 aux_names, work_load_list=None, logger=None,
                 sym_gen=None):
        if logger is None:
            logger = logging
        self.arg_names = arg_names
        self.param_names = param_names
        self.aux_names = aux_names
        self.ctx = ctx
        batch_size = train_data.batch_size
        if work_load_list is None:
            work_load_list = [1] * len(ctx)
        assert isinstance(work_load_list, list) and \
            len(work_load_list) == len(ctx)
        self.slices = _split_input_slice(batch_size, work_load_list)
        self.symbol = symbol
        self.sym_gen = sym_gen
        self.curr_execgrp = None
        self.execgrp = DataParallelExecutorGroup(
            symbol, arg_names, param_names, ctx, self.slices, train_data)
        if self.sym_gen is not None:
            self.execgrp_bucket = {train_data.default_bucket_key: self.execgrp}

    def install_monitor(self, monitor):
        if self.sym_gen is not None:
            raise NotImplementedError(
                "Monitoring is not implemented for bucketing")
        for train_exec in self.execgrp.train_execs:
            monitor.install(train_exec)

    def set_params(self, arg_params, aux_params):
        for ex in self.execgrp.train_execs:
            ex.copy_params_from(arg_params, aux_params)

    @staticmethod
    def _mean_out(names, blocks, dst):
        """Device-mean each replicated block into ``dst`` on host."""
        for name, replicas in zip(names, blocks):
            mean = sum(r.copyto(cpu()) for r in replicas) / len(replicas)
            mean.copyto(dst[name])

    def copy_to(self, arg_params, aux_params):
        """Copy (averaged over devices) params out (reference :300-310)."""
        self._mean_out(self.param_names, self.param_arrays, arg_params)
        self._mean_out(self.aux_names, self.aux_arrays, aux_params)

    @property
    def param_arrays(self):
        return self.curr_execgrp.param_arrays

    @property
    def grad_arrays(self):
        return self.curr_execgrp.grad_arrays

    @property
    def aux_arrays(self):
        return self.curr_execgrp.aux_arrays

    def _group_for(self, batch):
        """The executor group serving this batch: the sole group when
        not bucketing, else the bucket's group (built on first sight,
        sharing params with the default group)."""
        if self.sym_gen is None:
            return self.execgrp
        key = batch.bucket_key
        if key not in self.execgrp_bucket:
            self.execgrp_bucket[key] = DataParallelExecutorGroup(
                self.sym_gen(key), self.arg_names, self.param_names,
                self.ctx, self.slices, batch, shared_group=self.execgrp)
        return self.execgrp_bucket[key]

    def load_data_batch(self, data_batch):
        self.curr_execgrp = self._group_for(data_batch)
        self.curr_execgrp.load_data_batch(data_batch)

    def forward(self, is_train=False):
        self.curr_execgrp.forward(is_train=is_train)

    def backward(self):
        self.curr_execgrp.backward()

    def update_metric(self, metric, labels):
        self.curr_execgrp.update_metric(metric, labels)
