"""Legacy learning-rate scheduler module.

Parity: ``/root/reference/python/mxnet/misc.py`` — the original
``LearningRateScheduler``/``FactorScheduler`` pair that predates
``lr_scheduler.py``. Kept for API compatibility; new code should use
:mod:`mxnet_tpu.lr_scheduler`. Semantics match the reference: the factor
scheduler returns ``base_lr * factor**(iteration // step)`` and logs when
the rate changes.
"""
from __future__ import annotations

import logging
import math

__all__ = ["LearningRateScheduler", "FactorScheduler"]


class LearningRateScheduler:
    """Base class: maps an iteration count to a learning rate."""

    def __init__(self):
        self.base_lr = 0.01

    def __call__(self, iteration):
        raise NotImplementedError("must override this")


class FactorScheduler(LearningRateScheduler):
    """Reduce the learning rate by `factor` every `step` iterations."""

    def __init__(self, step, factor=0.1):
        super().__init__()
        if step < 1:
            raise ValueError("Schedule step must be greater or equal than 1 round")
        if factor >= 1.0:
            raise ValueError("Factor must be less than 1 to make lr reduce")
        self.step = step
        self.factor = factor
        self.old_lr = self.base_lr
        self.init = False

    def __call__(self, iteration):
        if not self.init:
            self.init = True
            self.old_lr = self.base_lr
        lr = self.base_lr * math.pow(self.factor, int(iteration / self.step))
        if lr != self.old_lr:
            self.old_lr = lr
            logging.info("At Iteration [%d]: Swith to new learning rate %.5f",
                         iteration, lr)
        return lr
