"""Legacy learning-rate scheduler module.

Parity: ``/root/reference/python/mxnet/misc.py`` — the original
``LearningRateScheduler``/``FactorScheduler`` pair that predates
``lr_scheduler.py``. Kept for API compatibility; new code should use
:mod:`mxnet_tpu.lr_scheduler`. Semantics match the reference: the factor
scheduler returns ``base_lr * factor**(iteration // step)`` and logs when
the rate changes.
"""
from __future__ import annotations

import logging

__all__ = ["LearningRateScheduler", "FactorScheduler"]


class LearningRateScheduler:
    """Base class: maps an iteration count to a learning rate. The owner
    (optimizer) assigns ``base_lr`` after construction, so the default
    here only matters for standalone use."""

    def __init__(self):
        self.base_lr = 0.01

    def __call__(self, iteration):
        raise NotImplementedError("must override this")


class FactorScheduler(LearningRateScheduler):
    """Staircase decay: multiply the rate by ``factor`` once per ``step``
    iterations, i.e. ``base_lr * factor**(iteration // step)``.

    The schedule itself is stateless (any iteration can be queried out
    of order); the only state is the last rate returned, kept so each
    decay is logged exactly once.
    """

    def __init__(self, step, factor=0.1):
        super().__init__()
        if step < 1:
            raise ValueError("step must be a positive iteration count")
        if factor >= 1.0:
            raise ValueError("factor must be < 1 so the rate decays")
        self.step = step
        self.factor = factor
        self.old_lr = None

    def __call__(self, iteration):
        if self.old_lr is None:
            self.old_lr = self.base_lr
        lr = self.base_lr * self.factor ** (iteration // self.step)
        if lr != self.old_lr:
            self.old_lr = lr
            logging.info("Iteration %d: learning rate decayed to %.5f",
                         iteration, lr)
        return lr
