"""mxnet_tpu: a TPU-native deep learning framework with the API surface of
dmlc-era MXNet (reference at /root/reference), rebuilt from scratch on
jax/XLA/pjit/Pallas.

Layer map (vs SURVEY.md §1): the reference's engine/storage/graph-executor
layers collapse into XLA's runtime and compiler; what remains user-visible —
NDArray, Symbol, Executor, KVStore, DataIter, FeedForward — is re-implemented
TPU-first here.
"""
from __future__ import annotations

import jax as _jax

# Honor explicit float64 dtypes (the reference supports f64 arrays; JAX
# truncates to f32 unless x64 is enabled). Python scalars stay weakly typed,
# so f32/bf16 compute paths are unaffected. NOTE: this is process-global; a
# host program mixing its own JAX code with this library will also see x64
# honored. Framework-internal code must therefore pass explicit dtypes (or
# python-float scalars) everywhere — never numpy float64 scalars.
_jax.config.update("jax_enable_x64", True)

from .base import MXNetError  # noqa: E402
from .context import Context, current_context, cpu, gpu, tpu, cpu_pinned  # noqa: E402
from . import ndarray  # noqa: E402
from . import ndarray as nd  # noqa: E402
from .ndarray import NDArray  # noqa: E402
from . import random  # noqa: E402
from . import symbol  # noqa: E402
from . import symbol as sym  # noqa: E402
from .symbol import Symbol, Group  # noqa: E402
from . import executor  # noqa: E402
from .executor import Executor  # noqa: E402
from . import operator  # noqa: E402
from .attribute import AttrScope  # noqa: E402
from .name import NameManager, Prefix  # noqa: E402
from . import optimizer  # noqa: E402
from . import metric  # noqa: E402
from . import initializer  # noqa: E402
from .initializer import Uniform, Normal, Orthogonal, Xavier, MSRAPrelu  # noqa: E402
from . import lr_scheduler  # noqa: E402
from . import misc  # noqa: E402
from . import telemetry  # noqa: E402
from . import profiler  # noqa: E402
from . import io  # noqa: E402
from . import kvstore  # noqa: E402
from . import kvstore as kv  # noqa: E402
# NOTE: kvstore_server is intentionally NOT imported here — importing it
# in a server/scheduler-role process joins the server loop (reference
# python/mxnet/kvstore_server.py:57-68 semantics); use
# `import mxnet_tpu.kvstore_server` explicitly, as the reference does.
from . import executor_manager  # noqa: E402
from . import callback  # noqa: E402
from . import monitor  # noqa: E402
from .monitor import Monitor  # noqa: E402
from . import model  # noqa: E402
from .model import FeedForward  # noqa: E402
from . import parallel  # noqa: E402
from .parallel import ParallelTrainer  # noqa: E402
from . import recordio  # noqa: E402
from . import image_io  # noqa: E402
from .image_io import ImageRecordIter, DeviceAugmentIter  # noqa: E402
from .io import DevicePrefetchIter  # noqa: E402
from . import distributed  # noqa: E402
from . import visualization  # noqa: E402
# reference short aliases (/root/reference/python/mxnet/__init__.py):
# mx.init, mx.viz, mx.mon, mx.rnd, mx.th
from . import initializer as init  # noqa: E402
from . import visualization as viz  # noqa: E402
from . import monitor as mon  # noqa: E402
from . import random as rnd  # noqa: E402
from . import rtc  # noqa: E402
from . import torch  # noqa: E402
from . import torch as th  # noqa: E402
from . import predict  # noqa: E402
from .predict import Predictor  # noqa: E402
from . import serving  # noqa: E402
from .serving import InferenceEngine  # noqa: E402
# after serving: the exposition server's /requests//healthz endpoints
# walk the engine registry, and MXNET_TELEMETRY_PORT arms it at import
from . import telemetry_http  # noqa: E402

__version__ = "0.1.0"
