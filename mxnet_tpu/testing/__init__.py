"""Testing utilities: deterministic fault injection for the distributed
transport (``mxnet_tpu.testing.faults``). Import cost is near-zero —
submodules are imported lazily by the tests that need them."""
from __future__ import annotations

__all__ = ["faults"]
