"""Deterministic fault injection for the ``dist_async`` transport.

The resilient kvstore client (``kvstore_dist.PSBackend._request``) can
survive dropped frames, severed connections, lost replies, and a
parameter server that is killed and restarted mid-run — but none of
those happen on a healthy localhost CI box. This module makes them
happen ON DEMAND, deterministically, so the retry/reconnect/dedup
machinery is exercised by fast tier-1 tests instead of only by
production outages.

Two injection surfaces:

* **Client transport faults** — a :class:`FaultInjector` installs
  itself as ``kvstore_dist._CLIENT_FAULTS`` while one of its context
  managers is active. Faults are a FIFO plan of directives consumed one
  per request attempt, so a test script reads like a fault schedule:

      inj = FaultInjector(seed=7)
      with inj.sever_connections(1):
          kv.push(...)        # first attempt severed, retry succeeds

  Randomized schedules (:meth:`FaultInjector.random_faults`) draw from
  the injector's own seeded RNG — the same seed always yields the same
  fault sequence, never from global random state.

* **Server crashes** — :func:`kill_server` / :func:`restart_server` /
  :func:`server_down` stop a live ``_Server`` and bring up a successor
  on the same port with the predecessor's state (store, updater, and
  retry-dedup table), the single-process stand-in for a parameter
  server recovering from its replica.

* **Serving-engine faults** — the same injector installs itself as
  ``serving.engine._SERVING_FAULTS`` and drives the engine's
  host-side failure seams deterministically:
  :meth:`FaultInjector.serving_h2d_failures` poisons individual
  requests (a bad host→device staging raises inside admission — the
  engine must retire ONLY that request),
  :meth:`FaultInjector.serving_round_hang` makes a dispatched round
  look permanently not-ready so the ``round_timeout_ms`` watchdog
  trips, and :meth:`FaultInjector.serving_crash_mid_round` raises
  :class:`InjectedCrash` after a decode dispatch — process death
  mid-round, the setup for ``engine.snapshot()`` →
  ``InferenceEngine.restore()`` kill-and-recover scenarios
  (tests/test_serving_faults.py).

* **Fleet faults** — the injector installs itself as
  ``serving.fleet._FLEET_FAULTS`` and drives the
  :class:`~mxnet_tpu.serving.FleetRouter`'s seams:
  :meth:`FaultInjector.fleet_kill_replica` (the named replica's next
  stepped round dies with :class:`InjectedCrash` via the engine's own
  crash seam — genuine mid-round death, dispatched-but-undrained),
  :meth:`FaultInjector.fleet_heartbeat_blackhole` (the replica's next
  pings go unanswered — dead-vs-slow discrimination and
  miss-threshold failover), :meth:`FaultInjector.fleet_slow_replica`
  (the channel to the replica stalls; the router's per-op timeout and
  ping probe decide slow-not-dead), and
  :meth:`FaultInjector.fleet_submit_failures` (the channel drops the
  submit — retry/backoff and the exactly-once adoption path), and
  :meth:`FaultInjector.fleet_handoff_failures` (the channel drops a
  KV-handoff delivery to a decode replica — same retry/dedup
  discipline on the disaggregated path). A directive naming replica
  ``None`` matches whichever replica reaches that seam first.

Every injected fault is appended to ``FaultInjector.log`` as
``(kind, op)`` so tests can assert the schedule actually fired.
"""
from __future__ import annotations

import collections
import contextlib
import random
import threading
import time

from .. import kvstore_dist as _kd

__all__ = ["FaultInjector", "InjectedCrash", "kill_server",
           "restart_server", "server_down"]


class InjectedCrash(RuntimeError):
    """Simulated process death mid-round (serving_crash_mid_round):
    deliberately NOT an MXNetError — the engine's per-request error
    isolation must not swallow it, exactly as it could not swallow a
    real SIGKILL."""


class FaultInjector:
    """A seeded, FIFO fault plan over the client-side transport.

    Directives (consumed one per ``_request`` send/recv attempt):

    * ``("drop",)``        — swallow the outgoing frame; the client
      blocks until its socket timeout, then retries (lost-packet path).
    * ``("delay", s)``     — sleep ``s`` seconds before sending
      (network stall / slow link).
    * ``("sever",)``       — close the connection instead of sending
      (peer reset mid-request; exercises reconnect).
    * ``("truncate",)``    — send half a length header, then close
      (connection dies mid-message; exercises the SERVER's half-frame
      handling too).
    * ``("drop_reply",)``  — let the request through, then discard the
      reply and kill the connection (the apply-then-lose-the-ack case
      that the server's sequence-number dedup exists for).
    * ``("pass",)``        — no fault (filler for randomized plans).
    """

    def __init__(self, seed=0):
        self.rng = random.Random(seed)
        self.plan = collections.deque()
        self.serving_plan = collections.deque()
        self.fleet_plan = collections.deque()
        self.log = []          # (kind, op) per injected fault
        self._depth = 0
        self._serving_depth = 0
        self._fleet_depth = 0
        self._hang_until = None
        self._lock = threading.Lock()

    # -- plan construction --------------------------------------------
    def random_faults(self, n, p_drop=0.0, p_sever=0.2, p_delay=0.0,
                      delay_s=0.05):
        """A deterministic (seeded) schedule of ``n`` directives, each
        independently a drop/sever/delay with the given probabilities
        (else a no-op). Returns the active context manager."""
        plan = []
        for _ in range(n):
            r = self.rng.random()
            if r < p_drop:
                plan.append(("drop",))
            elif r < p_drop + p_sever:
                plan.append(("sever",))
            elif r < p_drop + p_sever + p_delay:
                plan.append(("delay", delay_s))
            else:
                plan.append(("pass",))
        return self._scheduled(plan)

    def drop_sends(self, n=1):
        """Swallow the next ``n`` outgoing frames (timeout path)."""
        return self._scheduled([("drop",)] * n)

    def delay_sends(self, n=1, seconds=0.05):
        """Stall the next ``n`` sends by ``seconds`` each."""
        return self._scheduled([("delay", seconds)] * n)

    def sever_connections(self, n=1):
        """Close the connection instead of the next ``n`` sends."""
        return self._scheduled([("sever",)] * n)

    def close_mid_message(self, n=1):
        """Send a truncated frame then close, ``n`` times."""
        return self._scheduled([("truncate",)] * n)

    def drop_replies(self, n=1):
        """Lose the reply (after the server applied the request) for
        the next ``n`` round trips."""
        return self._scheduled([("drop_reply",)] * n)

    # -- serving-engine plans -----------------------------------------
    def serving_h2d_failures(self, n=1):
        """Fail the next ``n`` per-request host→device stagings inside
        engine admission (the poisoned-request case: each failure must
        retire ONLY its own request, with an error result)."""
        return self._serving_scheduled([("h2d_fail",)] * n)

    def serving_round_hang(self, seconds=0.5):
        """Make the next drained round look not-ready for ``seconds``
        (a wedged device dispatch): with ``round_timeout_ms`` set the
        engine's watchdog trips with ``EngineStuck`` — and once the
        hang passes, the round drains normally (recovery path)."""
        return self._serving_scheduled([("hang", seconds)])

    def serving_crash_mid_round(self, n=1):
        """Raise :class:`InjectedCrash` right after the next ``n``
        decode dispatches — the process dies mid-round with tokens
        dispatched but undrained, the snapshot()/restore() scenario."""
        return self._serving_scheduled([("crash",)] * n)

    @contextlib.contextmanager
    def _serving_scheduled(self, directives):
        from ..serving import engine as _se

        with self._lock:
            self.serving_plan.extend(directives)
            if self._serving_depth == 0:
                self._serving_prev = _se._SERVING_FAULTS
                _se._SERVING_FAULTS = self
            self._serving_depth += 1
        try:
            yield self
        finally:
            with self._lock:
                self._serving_depth -= 1
                if self._serving_depth == 0:
                    _se._SERVING_FAULTS = self._serving_prev
                    self.serving_plan.clear()
                    self._hang_until = None

    # -- hooks called by serving.engine (host-side seams only) --------
    def serving_h2d(self, req):
        """May raise: a per-request staging failure at admission."""
        with self._lock:
            head = self.serving_plan[0] if self.serving_plan else None
            if head is None or head[0] != "h2d_fail":
                return
            self.serving_plan.popleft()
        self.log.append(("h2d_fail", req.id))
        raise RuntimeError("fault injection: h2d failed for request "
                           "%r" % (req.id,))

    def serving_round_stuck(self):
        """True while a scheduled round-hang is active (the watchdog's
        readiness poll consults this; a real wedge would keep
        ``buffers_ready`` False the same way)."""
        with self._lock:
            if self._hang_until is None:
                head = (self.serving_plan[0] if self.serving_plan
                        else None)
                if head is None or head[0] != "hang":
                    return False
                self.serving_plan.popleft()
                self._hang_until = time.perf_counter() + head[1]
                self.log.append(("hang", head[1]))
            if time.perf_counter() < self._hang_until:
                return True
            self._hang_until = None
            return False

    def serving_crash(self):
        """May raise InjectedCrash: process death after dispatch."""
        with self._lock:
            head = self.serving_plan[0] if self.serving_plan else None
            if head is None or head[0] != "crash":
                return
            self.serving_plan.popleft()
        self.log.append(("crash", None))
        raise InjectedCrash("fault injection: process died mid-round "
                            "(dispatched, undrained)")

    # -- fleet plans ---------------------------------------------------
    def fleet_kill_replica(self, replica_id=None, n=1):
        """Kill the named replica (or whichever steps first when
        ``None``) mid-round, ``n`` times: its next stepped round dies
        with :class:`InjectedCrash` AFTER dispatch via the engine's
        own crash seam — tokens dispatched but undrained, exactly the
        snapshot-after-crash state the router must fail over from."""
        return self._fleet_scheduled([("kill_replica", replica_id)] * n)

    def fleet_heartbeat_blackhole(self, replica_id=None, n=1):
        """The replica's next ``n`` heartbeat pings go unanswered (a
        partitioned or hung peer): ``heartbeat_misses`` consecutive
        misses and the router declares it dead and fails over."""
        return self._fleet_scheduled([("blackhole", replica_id)] * n)

    def fleet_slow_replica(self, replica_id=None, seconds=1.0, n=1):
        """The channel to the replica stalls ``seconds`` on the next
        ``n`` submits. Past the router's ``timeout_ms`` the op times
        out and the ping probe decides slow-not-dead (retry, no
        failover) — under it, the submit just lands."""
        return self._fleet_scheduled(
            [("slow", replica_id, seconds)] * n)

    def fleet_submit_failures(self, replica_id=None, n=1):
        """Drop the next ``n`` submits to the replica on the floor
        (``ConnectionError`` from the channel): the router's bounded
        retry/backoff — and, when the submit actually LANDED before
        the fault, the exactly-once adoption path — must absorb it."""
        return self._fleet_scheduled([("submit_fail", replica_id)] * n)

    def fleet_handoff_failures(self, replica_id=None, n=1):
        """Drop the next ``n`` KV-handoff deliveries to the decode
        replica (``ConnectionError`` from the channel): the router's
        retry must re-deliver the SAME package, and when the admit
        landed before the fault died on the wire, the decode engine's
        dedup table must admit exactly once (adoption, not double
        admission)."""
        return self._fleet_scheduled([("handoff_fail", replica_id)] * n)

    @contextlib.contextmanager
    def _fleet_scheduled(self, directives):
        from ..serving import fleet as _sf

        with self._lock:
            self.fleet_plan.extend(directives)
            if self._fleet_depth == 0:
                self._fleet_prev = _sf._FLEET_FAULTS
                _sf._FLEET_FAULTS = self
            self._fleet_depth += 1
        try:
            yield self
        finally:
            with self._lock:
                self._fleet_depth -= 1
                if self._fleet_depth == 0:
                    _sf._FLEET_FAULTS = self._fleet_prev
                    self.fleet_plan.clear()

    # -- hooks called by serving.fleet.FleetRouter --------------------
    def _fleet_head(self, kind, replica_id):
        """Pop-and-return the head directive iff it is ``kind`` aimed
        at ``replica_id`` (or at anyone). FIFO: a head aimed at a
        DIFFERENT replica blocks this one from matching, so a test's
        schedule fires in the order it was written."""
        head = self.fleet_plan[0] if self.fleet_plan else None
        if head is None or head[0] != kind:
            return None
        if head[1] is not None and head[1] != replica_id:
            return None
        return self.fleet_plan.popleft()

    def fleet_step_context(self, replica_id):
        """Context manager for one replica round, or None. A matched
        kill directive arms the ENGINE crash seam for the round's
        scope, so death lands after dispatch exactly like
        :meth:`serving_crash_mid_round`."""
        with self._lock:
            head = self._fleet_head("kill_replica", replica_id)
        if head is None:
            return None
        self.log.append(("kill_replica", replica_id))
        return self._serving_scheduled([("crash",)])

    def fleet_ping_blackholed(self, replica_id):
        """True when the replica's ping should go unanswered."""
        with self._lock:
            head = self._fleet_head("blackhole", replica_id)
        if head is None:
            return False
        self.log.append(("blackhole", replica_id))
        return True

    def fleet_submit(self, replica_id):
        """Channel fault for one submit attempt: raises
        ``ConnectionError`` (dropped), or returns a stall in seconds
        (the router judges it against its timeout), or 0 (clean)."""
        with self._lock:
            head = self._fleet_head("submit_fail", replica_id)
            if head is None:
                slow = self._fleet_head("slow", replica_id)
            else:
                slow = None
        if head is not None:
            self.log.append(("submit_fail", replica_id))
            raise ConnectionError(
                "fault injection: submit to replica %r lost"
                % (replica_id,))
        if slow is not None:
            self.log.append(("slow", replica_id))
            return slow[2]
        return 0

    def fleet_handoff(self, replica_id):
        """Channel fault for one KV-handoff delivery attempt: raises
        ``ConnectionError`` (package lost on the wire), or returns a
        stall in seconds, or 0 (clean) — same contract as
        :meth:`fleet_submit`, separate directive kind so a schedule
        can fault handoffs without touching ordinary submits."""
        with self._lock:
            head = self._fleet_head("handoff_fail", replica_id)
            if head is None:
                slow = self._fleet_head("slow", replica_id)
            else:
                slow = None
        if head is not None:
            self.log.append(("handoff_fail", replica_id))
            raise ConnectionError(
                "fault injection: handoff to replica %r lost"
                % (replica_id,))
        if slow is not None:
            self.log.append(("slow", replica_id))
            return slow[2]
        return 0

    @contextlib.contextmanager
    def _scheduled(self, directives):
        with self._lock:
            self.plan.extend(directives)
            if self._depth == 0:
                self._prev = _kd._CLIENT_FAULTS
                _kd._CLIENT_FAULTS = self
            self._depth += 1
        try:
            yield self
        finally:
            with self._lock:
                self._depth -= 1
                if self._depth == 0:
                    _kd._CLIENT_FAULTS = self._prev
                    self.plan.clear()  # unconsumed faults die with scope

    # -- hooks called by kvstore_dist._request ------------------------
    def before_send(self, server, envelope, conn):
        """Return False to suppress the real send (frame dropped)."""
        with self._lock:
            head = self.plan[0] if self.plan else None
            if head is None or head[0] == "drop_reply":
                return True  # drop_reply waits for after_recv
            self.plan.popleft()
        op = envelope[3][0]
        kind = head[0]
        if kind == "pass":
            return True
        self.log.append((kind, op))
        if kind == "drop":
            return False
        if kind == "delay":
            time.sleep(head[1])
            return True
        if kind == "sever":
            conn.close()
            raise ConnectionError("fault injection: connection severed "
                                  "before send")
        if kind == "truncate":
            try:
                conn.sendall(b"\x00\x00\x00\x00")  # half a length prefix
            finally:
                conn.close()
            raise ConnectionError("fault injection: connection closed "
                                  "mid-message")
        raise AssertionError("unknown fault directive %r" % (head,))

    def after_recv(self, server, envelope, reply, conn):
        with self._lock:
            head = self.plan[0] if self.plan else None
            if head is None or head[0] != "drop_reply":
                return
            self.plan.popleft()
        self.log.append(("drop_reply", envelope[3][0]))
        conn.close()
        raise ConnectionError("fault injection: reply lost")


# -- server crash / recovery ------------------------------------------

def kill_server(owner):
    """Stop a live ``_Server`` (listener + every accepted connection),
    as a crash would. ``owner`` is a ``PSBackend`` or a ``_Server``;
    returns the dead server (its in-memory state survives for
    :func:`restart_server`)."""
    server = getattr(owner, "server", owner)
    server.close()
    return server


def restart_server(owner, dead=None):
    """Bring up a successor ``_Server`` on the dead one's port with its
    whole state (store, updater, retry-dedup table, and the shared
    lock/condition, so a predecessor handler still mid-apply publishes
    where successor waiters can see it) — a parameter server recovering
    from its replica. Rebinds ``owner.server`` when ``owner`` is a
    ``PSBackend``. Returns the new server."""
    old = dead if dead is not None else getattr(owner, "server", owner)
    new = _kd._Server(old.rank, old.port, predecessor=old)
    new.start()
    if hasattr(owner, "server"):
        owner.server = new
    return new


@contextlib.contextmanager
def server_down(backend, restart_after=None):
    """The backend's colocated server is DEAD inside the block.

    With ``restart_after`` set, a timer restarts it that many seconds
    in — so a client request issued inside the block retries against a
    refused port and then succeeds against the successor, the
    kill-and-recover scenario. Without it, the server stays down until
    the block exits (then it is restarted)."""
    dead = kill_server(backend)
    restarted = threading.Event()

    def _revive():
        restart_server(backend, dead)
        restarted.set()

    timer = None
    if restart_after is not None:
        timer = threading.Timer(restart_after, _revive)
        timer.daemon = True
        timer.start()
    try:
        yield dead
    finally:
        if timer is not None:
            timer.join()
        if not restarted.is_set():
            _revive()
