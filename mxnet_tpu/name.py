"""Automatic symbol naming.

Parity: ``/root/reference/python/mxnet/name.py`` — NameManager assigns
``<hint><counter>`` names to anonymous symbols; Prefix prepends a prefix.
"""
from __future__ import annotations

__all__ = ["NameManager", "Prefix"]


class NameManager:
    """Assign unique names to anonymous symbols."""

    _current = None

    def __init__(self):
        self._counter = {}
        self._old = None

    def get(self, name, hint):
        if name is not None:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = "%s%d" % (hint, self._counter[hint])
        self._counter[hint] += 1
        return name

    def __enter__(self):
        self._old = NameManager._current
        NameManager._current = self
        return self

    def __exit__(self, ptype, value, trace):
        NameManager._current = self._old

    @staticmethod
    def current():
        if NameManager._current is None:
            NameManager._current = NameManager()
        return NameManager._current


class Prefix(NameManager):
    """NameManager that always prepends a prefix (reference name.py:40)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name
