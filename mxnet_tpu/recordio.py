"""RecordIO: read/write the dmlc record container + image record packing.

Parity: ``python/mxnet/recordio.py`` (MXRecordIO, IRHeader, pack/unpack,
pack_img/unpack_img) over the same binary format, so ``.rec`` datasets
interchange with the reference. Uses the native C++ library when built
(``cpp/recordio.cc``); otherwise a pure-Python implementation of the
identical format (magic 0xced7230a, cflag/length word, 4-byte alignment,
magic-split multi-part records).
"""
from __future__ import annotations

import ctypes
import os
import struct
from collections import namedtuple

import numpy as np

from .base import MXNetError
from .libinfo import get_lib, check_call

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img", "list_record_offsets"]

_MAGIC = 0xced7230a


# ---------------------------------------------------------------------------
# pure-python fallback engines

class _PyWriter:
    def __init__(self, path):
        self._f = open(path, "wb")
        self.tell_ = 0

    def write(self, buf):
        if len(buf) >= (1 << 29):
            raise MXNetError("record too large")
        magic = struct.pack("<I", _MAGIC)
        n = len(buf)
        lower = (n >> 2) << 2
        upper = ((n + 3) >> 2) << 2
        dptr = 0
        out = []
        for i in range(0, lower, 4):
            if buf[i:i + 4] == magic:
                out.append(magic)
                out.append(struct.pack("<I", ((1 if dptr == 0 else 2) << 29)
                                       | (i - dptr)))
                out.append(buf[dptr:i])
                dptr = i + 4
        out.append(magic)
        out.append(struct.pack("<I", ((3 if dptr else 0) << 29) | (n - dptr)))
        out.append(buf[dptr:n])
        out.append(b"\x00" * (upper - n))
        blob = b"".join(out)
        self._f.write(blob)
        self.tell_ += len(blob)

    def tell(self):
        return self.tell_

    def close(self):
        self._f.close()


class _PyReader:
    def __init__(self, path):
        self._f = open(path, "rb")

    def read(self):
        parts = []
        multi = False
        while True:
            head = self._f.read(8)
            if len(head) < 8:
                return None
            magic, lrec = struct.unpack("<II", head)
            if magic != _MAGIC:
                raise MXNetError("recordio: bad magic")
            cflag, n = lrec >> 29, lrec & ((1 << 29) - 1)
            if multi:
                parts.append(struct.pack("<I", _MAGIC))
            data = self._f.read(n)
            if len(data) != n:
                raise MXNetError("recordio: truncated payload")
            pad = (((n + 3) >> 2) << 2) - n
            if pad:
                self._f.read(pad)
            parts.append(data)
            if cflag in (0, 3):
                return b"".join(parts)
            multi = True

    def seek(self, pos):
        self._f.seek(pos)

    def tell(self):
        return self._f.tell()

    def close(self):
        self._f.close()


# ---------------------------------------------------------------------------

class MXRecordIO:
    """Read/write RecordIO files (reference recordio.py:MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.is_open = False
        self.open()

    def open(self):
        lib = get_lib()
        self._lib = lib
        if self.flag == "w":
            self.writable = True
        elif self.flag == "r":
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        if lib is not None:
            self.handle = ctypes.c_void_p()
            fn = (lib.MXTRecordIOWriterCreate if self.writable
                  else lib.MXTRecordIOReaderCreate)
            check_call(fn(ctypes.c_char_p(self.uri.encode()),
                          ctypes.byref(self.handle)))
        else:
            self.handle = (_PyWriter(self.uri) if self.writable
                           else _PyReader(self.uri))
        self.is_open = True

    def close(self):
        if not self.is_open:
            return
        if self._lib is not None:
            fn = (self._lib.MXTRecordIOWriterFree if self.writable
                  else self._lib.MXTRecordIOReaderFree)
            check_call(fn(self.handle))
        else:
            self.handle.close()
        self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def reset(self):
        """Reopen (truncates in 'w' mode) — reference semantics."""
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        if isinstance(buf, str):
            buf = buf.encode()
        if self._lib is not None:
            check_call(self._lib.MXTRecordIOWriterWriteRecord(
                self.handle, ctypes.c_char_p(bytes(buf)),
                ctypes.c_size_t(len(buf))))
        else:
            self.handle.write(bytes(buf))

    def read(self):
        assert not self.writable
        if self._lib is not None:
            buf = ctypes.c_char_p()
            size = ctypes.c_size_t()
            check_call(self._lib.MXTRecordIOReaderReadRecord(
                self.handle, ctypes.byref(buf), ctypes.byref(size)))
            if not buf:  # NULL pointer -> EOF
                return None
            return ctypes.string_at(buf, size.value)
        return self.handle.read()

    def tell(self):
        if self._lib is not None:
            pos = ctypes.c_uint64()
            fn = (self._lib.MXTRecordIOWriterTell if self.writable
                  else self._lib.MXTRecordIOReaderTell)
            check_call(fn(self.handle, ctypes.byref(pos)))
            return pos.value
        return self.handle.tell()

    def seek(self, pos):
        assert not self.writable
        if self._lib is not None:
            check_call(self._lib.MXTRecordIOReaderSeek(
                self.handle, ctypes.c_uint64(pos)))
        else:
            self.handle.seek(pos)


class MXIndexedRecordIO(MXRecordIO):
    """RecordIO with a 'key\\toffset' index sidecar for random access
    (reference recordio.py:MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.isfile(idx_path):
            with open(idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) != 2:
                        continue
                    key = key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if self.is_open and self.writable:
            with open(self.idx_path, "w") as f:
                for k in self.keys:
                    f.write("%s\t%d\n" % (k, self.idx[k]))
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        self.idx[key] = self.tell()
        self.keys.append(key)
        self.write(buf)


def list_record_offsets(uri, idx_path=None):
    """Byte offsets of every record in a RecordIO file, in file order.

    The decode-worker pool shards these offsets into batches
    (image_io._ParallelEngine); each worker then random-accesses its own
    records via ``seek``. When the ``MXIndexedRecordIO`` sidecar is
    named (``idx_path``) and exists, the offsets come from it directly —
    O(keys) text read instead of decoding every record frame; otherwise
    the container is scanned once.
    """
    if idx_path is not None and os.path.isfile(idx_path):
        offsets = []
        try:
            with open(idx_path) as f:
                for line in f:
                    if not line.strip():
                        continue  # trailing newline etc.
                    parts = line.strip().split("\t")
                    if len(parts) != 2:
                        raise ValueError("malformed index line")
                    offsets.append(int(parts[1]))
        except ValueError:
            # malformed line (a writer died mid-line): fails the sanity
            # check below, taking the same warn-and-scan degrade path a
            # stale sidecar does
            offsets = [-1]
        # index files follow write order, but sort defensively: the
        # epoch order must be the file order the scan would produce.
        # A stale/truncated sidecar (rec regenerated, old idx left
        # behind, offset digits cut short) would silently shrink or
        # mis-map the epoch — cheap sanity checks make that loud and
        # fall back to the scan. The magic probe at the LAST offset
        # catches numerically-plausible corruption (a truncated offset
        # still in bounds) without decoding anything.
        offsets = sorted(offsets)
        size = os.path.getsize(uri)
        if offsets:
            ok = (offsets[0] == 0 and offsets[-1] < size
                  and all(b > a for a, b in zip(offsets, offsets[1:])))
            if ok:
                with open(uri, "rb") as f:
                    f.seek(offsets[-1])
                    ok = f.read(4) == struct.pack("<I", _MAGIC)
            if ok:
                return offsets
            import logging
            logging.warning(
                "list_record_offsets: index %s does not fit %s "
                "(stale/truncated sidecar?) — falling back to a full "
                "scan", idx_path, uri)
    reader = MXRecordIO(uri, "r")
    offsets = []
    try:
        while True:
            pos = reader.tell()
            if reader.read() is None:
                break
            offsets.append(pos)
    finally:
        reader.close()
    return offsets


# ---------------------------------------------------------------------------
# image record packing (reference recordio.py IRHeader/pack/unpack)

IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IRFormat = "<IfQQ"
_IRSize = struct.calcsize(_IRFormat)


def pack(header, s):
    """Pack a header + raw bytes into an image-record payload."""
    header = IRHeader(*header)
    if isinstance(header.label, (np.ndarray, list, tuple)):
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0.0)
        s = label.tobytes() + s
    return struct.pack(_IRFormat, *header) + s


def unpack(s):
    """Unpack an image-record payload to (IRHeader, bytes)."""
    header = IRHeader(*struct.unpack(_IRFormat, s[:_IRSize]))
    s = s[_IRSize:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


#: raw (unencoded) image payload: magic + u16 height + u16 width + u8
#: channels, then HWC BGR/gray uint8 pixels. A lossless fast path that
#: skips JPEG decode entirely (the reference's im2rec likewise stores
#: raw pixels when encoding is disabled; cpp/image_iter.cc reads it
#: zero-copy).
_RAW_MAGIC = b"RAW0"


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode a HxWx3 (RGB) / HxW uint8 array and pack it.

    ``img_fmt=".raw"`` stores unencoded pixels (lossless, ~4x faster to
    read back on one core: no JPEG decode)."""
    import struct

    if img_fmt == ".raw":
        a = np.ascontiguousarray(
            img[:, :, ::-1] if img.ndim == 3 else img, dtype=np.uint8)
        h, w = a.shape[:2]
        c = a.shape[2] if a.ndim == 3 else 1
        blob = (_RAW_MAGIC + struct.pack("<HHB", h, w, c) + a.tobytes())
        return pack(header, blob)
    import cv2
    if img.ndim == 3:
        img = img[:, :, ::-1]  # RGB -> BGR for OpenCV encoding
    if img_fmt in (".jpg", ".jpeg"):
        params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif img_fmt == ".png":
        params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
    else:
        params = []
    ok, buf = cv2.imencode(img_fmt, img, params)
    if not ok:
        raise MXNetError("pack_img: encode failed")
    return pack(header, buf.tobytes())


def unpack_img(s, iscolor=-1):
    """Unpack to (IRHeader, decoded RGB/gray ndarray)."""
    import struct

    header, blob = unpack(s)
    if blob[:4] == _RAW_MAGIC:
        h, w, c = struct.unpack("<HHB", blob[4:9])
        a = np.frombuffer(blob[9:9 + h * w * c], np.uint8)
        a = a.reshape((h, w) if c == 1 else (h, w, c))
        if a.ndim == 3:
            a = a[:, :, ::-1]  # stored BGR -> RGB
        if iscolor == 0 and a.ndim == 3:
            import cv2
            a = cv2.cvtColor(np.ascontiguousarray(a[:, :, ::-1]),
                             cv2.COLOR_BGR2GRAY)
        elif iscolor == 1 and a.ndim == 2:
            a = np.repeat(a[:, :, None], 3, axis=2)
        return header, a
    import cv2
    img = cv2.imdecode(np.frombuffer(blob, dtype=np.uint8), iscolor)
    if img is None:
        raise MXNetError("unpack_img: decode failed")
    if img.ndim == 3:
        img = img[:, :, ::-1]  # BGR -> RGB
    return header, img
