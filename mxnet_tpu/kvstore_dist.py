"""``dist_async``: a real update-per-push parameter server.

The reference's async mode lives in ps-lite server processes: every
worker push is applied to the weights immediately, with NO worker
lockstep (``/root/reference/src/kvstore/kvstore_dist_server.h:194-202``);
workers pull whatever the current weights are. XLA collectives cannot
express that (they are synchronous by construction), so this backend is
deliberately HOST-driven, like the reference's: each process runs one
server thread (the reference colocates via ps-lite roles; here every
worker hosts a server, so ``-n N`` gives N servers like ``num_servers =
num_workers`` launches), and requests ride length-prefixed pickle over
TCP where ps-lite rode ZMQ.

Key placement mirrors ``EncodeKey`` (``kvstore_dist.h:230-268``):

* small keys hash to one server: ``(key * 9973) % num_servers``;
* arrays >= ``MXNET_KVSTORE_BIGARRAY_BOUND`` are RANGE-PARTITIONED along
  their first axis across all servers, so no single host stores or
  updates a whole embedding-sized array.

Updates run in the owning server's thread, serialized per server by the
request loop (the reference serializes through the ps handler thread) —
``updater(key, recv, stored)`` with the optimizer the workers sent via
``set_optimizer`` (pickled, command 0 in the reference protocol).

Server addresses: ``MXNET_KVSTORE_SERVER_HOSTS`` (comma list, one per
process) or 127.0.0.1 for single-machine multi-process runs;
``MXNET_KVSTORE_PORT_BASE`` (default 24500) + rank.
"""
from __future__ import annotations

import logging
import os
import pickle
import socket
import struct
import threading

import numpy as np

from .base import MXNetError
from .kvstore import _bigarray_bound  # single source for the threshold

__all__ = ["PSBackend"]

_LEN = struct.Struct("!Q")

# SECURITY: the wire format is pickle, and ``pickle.loads`` on attacker
# bytes is remote code execution. Like ps-lite's ZMQ, this transport
# assumes a TRUSTED private cluster network. Set
# ``MXNET_KVSTORE_SECRET`` (any shared string, exported to every
# process — tools/launch.py forwards env) to require an HMAC-SHA256 tag
# on every message, rejecting frames from anything that doesn't hold
# the secret. Do NOT expose the server port beyond the cluster.


def _secret():
    return os.environ.get("MXNET_KVSTORE_SECRET", "").encode()


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sec = _secret()
    if sec:
        import hmac
        payload += hmac.new(sec, payload, "sha256").digest()
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    payload = _recv_exact(sock, n)
    sec = _secret()
    if sec:
        import hmac
        if len(payload) < 32:
            raise MXNetError("kvstore dist_async: short frame under "
                             "MXNET_KVSTORE_SECRET")
        payload, tag = payload[:-32], payload[-32:]
        want = hmac.new(sec, payload, "sha256").digest()
        if not hmac.compare_digest(tag, want):
            raise MXNetError(
                "kvstore dist_async: HMAC verification failed — peer "
                "does not hold MXNET_KVSTORE_SECRET (refusing to "
                "unpickle untrusted bytes)")
    return pickle.loads(payload)


def _port_base():
    if "MXNET_KVSTORE_PORT_BASE" in os.environ:
        return int(os.environ["MXNET_KVSTORE_PORT_BASE"])
    # derive from the coordinator port so concurrent launches on one
    # machine (each with its own free coordinator port) don't collide
    coord = os.environ.get("MXNET_TPU_COORDINATOR")
    if coord and ":" in coord:
        return int(coord.rsplit(":", 1)[1]) + 1000
    return 24500


class _Server(threading.Thread):
    """One server thread: owns a slice of the key space; applies pushes
    immediately (async semantics). Daemon — dies with the process."""

    def __init__(self, rank, port):
        super().__init__(daemon=True, name="mxnet-ps-server-%d" % rank)
        self.rank = rank
        self.store = {}        # (key, part) -> np.ndarray
        self.updater = None
        self.lock = threading.Lock()
        self.conns = []        # accepted sockets — see close()
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self.sock.bind(("0.0.0.0", port))
        except OSError as e:
            raise MXNetError(
                "dist_async: cannot bind parameter-server port %d (%s). "
                "Another job on this host owns it — set "
                "MXNET_KVSTORE_PORT_BASE to a free range." % (port, e))
        self.sock.listen(64)

    def run(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return  # socket closed at shutdown
            with self.lock:
                self.conns.append(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def close(self):
        """Close the listener AND every accepted connection: on Linux an
        ESTABLISHED accepted socket still counts as bound to the port,
        so a successor server could not re-bind until they are gone
        (SO_REUSEADDR only covers TIME_WAIT)."""
        try:
            self.sock.close()
        except OSError:
            pass
        with self.lock:
            conns, self.conns = self.conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def _serve(self, conn):
        try:
            while True:
                msg = _recv_msg(conn)
                op = msg[0]
                if op == "init":
                    _, key, part, val = msg
                    with self.lock:
                        # first init wins (every worker inits every key)
                        self.store.setdefault((key, part), val.copy())
                    _send_msg(conn, ("ok",))
                elif op == "push":
                    _, key, part, val = msg
                    with self.lock:
                        if (key, part) not in self.store:
                            _send_msg(conn, ("err",
                                             "key %s not init" % key))
                            continue
                        stored = self.store[(key, part)]
                        if self.updater is not None:
                            # update-per-push, reference
                            # kvstore_dist_server.h:194-202
                            from . import ndarray as nd
                            recv = nd.array(val)
                            dst = nd.array(stored)
                            self.updater(key, recv, dst)
                            self.store[(key, part)] = dst.asnumpy()
                        else:
                            # no updater: plain overwrite-with-merged,
                            # like the reference server without optimizer
                            self.store[(key, part)] = val.copy()
                    _send_msg(conn, ("ok",))
                elif op == "pull":
                    _, key, part = msg
                    with self.lock:
                        val = self.store.get((key, part))
                    if val is None:
                        _send_msg(conn, ("err", "key %s not init" % key))
                    else:
                        _send_msg(conn, ("ok", val))
                elif op == "set_optimizer":
                    from . import optimizer as opt_mod
                    optimizer = pickle.loads(msg[1])
                    with self.lock:
                        if isinstance(optimizer, opt_mod.Optimizer):
                            self.updater = opt_mod.get_updater(optimizer)
                        else:
                            self.updater = optimizer  # pre-built updater
                    _send_msg(conn, ("ok",))
                elif op == "stop":
                    _send_msg(conn, ("ok",))
                    return
                else:
                    _send_msg(conn, ("err", "bad op %r" % (op,)))
        except (ConnectionError, EOFError):
            pass
        except BaseException:
            # a dying server thread must not be silent: the peer only
            # sees a connection reset with no cause
            import traceback
            logging.error("parameter server %d: handler crashed:\n%s",
                          self.rank, traceback.format_exc())
        finally:
            conn.close()


class PSBackend:
    """Worker-side client + this process's colocated server.

    One live backend per process (like one ps-lite van per process):
    creating a new dist_async store closes the previous backend's
    sockets first — GC cannot be relied on to run ``close()`` before
    the new server binds the same port, because the server THREAD
    object stays registered in ``threading`` while its accept loop
    runs. Sequential store lifetimes only; two concurrently-used
    dist_async stores in one process are not supported (they weren't
    in the reference either — one ps-lite customer id per role).
    """

    _live = None
    _generation = 0

    def __init__(self):
        import jax
        if PSBackend._live is not None:
            PSBackend._live.close()
            PSBackend._live = None
        # each store generation gets a fresh port block: even after
        # close(), peer-held FIN_WAIT sockets keep the OLD ports bound
        # on Linux, so re-binding them is not reliable. Store creation
        # is collective (every process creates stores in the same
        # order), so the generation — and thus the port map — agrees
        # across processes without communication.
        PSBackend._generation += 1
        self.generation = PSBackend._generation
        self.rank = jax.process_index()
        self.nserv = jax.process_count()
        hosts = os.environ.get("MXNET_KVSTORE_SERVER_HOSTS")
        if hosts:
            self.hosts = [h.strip() for h in hosts.split(",")]
            if len(self.hosts) != self.nserv:
                raise MXNetError(
                    "MXNET_KVSTORE_SERVER_HOSTS lists %d hosts for %d "
                    "processes" % (len(self.hosts), self.nserv))
        else:
            self.hosts = ["127.0.0.1"] * self.nserv
        self.server = _Server(self.rank, self._port(self.rank))
        self.server.start()
        self._conns = {}
        self._lock = threading.Lock()
        self._layout = {}  # key -> [(server, slice)] fixed at init
        # make sure every server is listening before anyone pushes
        from . import distributed
        distributed.barrier("ps_backend_up")
        PSBackend._live = self
        logging.info("dist_async parameter server up: rank %d/%d",
                     self.rank, self.nserv)

    def _port(self, server):
        return _port_base() + (self.generation - 1) * self.nserv + server

    # -- transport ----------------------------------------------------
    def _conn_locked(self, server):
        c = self._conns.get(server)
        if c is None:
            # generous timeout: on oversubscribed test hosts a peer can
            # legitimately stall for minutes inside an XLA compile; a
            # DEAD peer is detected by TCP reset, not by idleness
            # (ps-lite likewise waits on its van). Override with
            # MXNET_KVSTORE_TIMEOUT (seconds).
            c = socket.create_connection(
                (self.hosts[server], self._port(server)),
                timeout=float(os.environ.get("MXNET_KVSTORE_TIMEOUT",
                                             "600")))
            self._conns[server] = c
        return c

    def _request(self, server, msg):
        try:
            with self._lock:  # one in-flight request per worker (like
                c = self._conn_locked(server)  # the engine var
                _send_msg(c, msg)              # serializing pushes)
                reply = _recv_msg(c)
        except (ConnectionError, socket.timeout, OSError) as e:
            # a dead/unreachable server is a cluster failure, not a bug
            # in the caller: name the peer so the operator can act (the
            # reference's ps-lite likewise aborts the run when a server
            # van connection drops)
            with self._lock:
                stale = self._conns.pop(server, None)
            if stale is not None:
                try:
                    stale.close()
                except OSError:
                    pass
            raise MXNetError(
                "dist_async: parameter server %d (%s:%d) is unreachable "
                "or died mid-request (%s: %s). The key range it owned "
                "is lost; restart the job from the last checkpoint."
                % (server, self.hosts[server], self._port(server),
                   type(e).__name__, e))
        if reply[0] != "ok":
            raise MXNetError("parameter server: %s" % (reply[1],))
        return reply[1] if len(reply) > 1 else None

    # -- key placement (reference EncodeKey, kvstore_dist.h:230-268) --
    def _owner(self, key):
        return (key * 9973) % self.nserv

    def _partition(self, key, shape):
        """[(server, slice)] — whole-array for small keys, first-axis
        ranges across every server for big ones."""
        size = int(np.prod(shape)) if shape else 1
        if size < _bigarray_bound() or not shape or shape[0] < self.nserv:
            return [(self._owner(key), slice(None))]
        rows = shape[0]
        per = -(-rows // self.nserv)
        parts = []
        for s in range(self.nserv):
            lo = min(s * per, rows)
            hi = min(lo + per, rows)
            if lo < hi:
                parts.append((s, slice(lo, hi)))
        return parts

    # -- API ----------------------------------------------------------
    def init(self, key, value):
        value = np.asarray(value)
        self._layout[key] = self._partition(key, value.shape)
        for part, (server, sl) in enumerate(self._layout[key]):
            self._request(server, ("init", key, part, value[sl]))

    def push(self, key, value):
        value = np.asarray(value)
        for part, (server, sl) in enumerate(self._layout[key]):
            self._request(server, ("push", key, part, value[sl]))

    def pull(self, key):
        parts = [self._request(server, ("pull", key, part))
                 for part, (server, _) in enumerate(self._layout[key])]
        if len(parts) == 1:
            return parts[0]
        return np.concatenate(parts, axis=0)

    def set_optimizer(self, pickled):
        for s in range(self.nserv):
            self._request(s, ("set_optimizer", pickled))

    def close(self):
        """Finalize the parameter-server backend (reference ps-lite
        Postoffice::Finalize semantics): BARRIER FIRST, then close
        sockets. The barrier must come before ANY server shard goes
        away — a worker that finishes early and tears down its server
        while a slow peer is still pulling kills that peer with a
        connection reset (observed as the 1-core 4-worker flake: ranks
        1-3 GC'd their kvstore while rank 0 was mid-pull on the key
        range rank 2's server owned). Idempotent: only the first close
        barriers and closes, so a second close can never deadlock
        waiting for peers that already left."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        try:
            from jax.experimental import multihost_utils
            # If a peer DIED before reaching this barrier, the jax
            # coordination service detects the missing heartbeat and
            # aborts the collective (it does not hang forever) — the
            # same unhappy-path contract as ps-lite's Finalize barrier.
            multihost_utils.sync_global_devices("kvstore_ps_close")
        except Exception:
            pass  # interpreter teardown / single process: best effort
        with self._lock:
            for c in self._conns.values():
                try:
                    c.close()
                except OSError:
                    pass
            self._conns.clear()
        self.server.close()
        if PSBackend._live is self:
            PSBackend._live = None
