"""``dist_async``: a real update-per-push parameter server.

The reference's async mode lives in ps-lite server processes: every
worker push is applied to the weights immediately, with NO worker
lockstep (``/root/reference/src/kvstore/kvstore_dist_server.h:194-202``);
workers pull whatever the current weights are. XLA collectives cannot
express that (they are synchronous by construction), so this backend is
deliberately HOST-driven, like the reference's: each process runs one
server thread (the reference colocates via ps-lite roles; here every
worker hosts a server, so ``-n N`` gives N servers like ``num_servers =
num_workers`` launches), and requests ride length-prefixed pickle over
TCP where ps-lite rode ZMQ.

Key placement mirrors ``EncodeKey`` (``kvstore_dist.h:230-268``):

* small keys hash to one server: ``(key * 9973) % num_servers``;
* arrays >= ``MXNET_KVSTORE_BIGARRAY_BOUND`` are RANGE-PARTITIONED along
  their first axis across all servers, so no single host stores or
  updates a whole embedding-sized array.

Updates run in the owning server's thread, serialized per server by the
request loop (the reference serializes through the ps handler thread) —
``updater(key, recv, stored)`` with the optimizer the workers sent via
``set_optimizer`` (pickled, command 0 in the reference protocol).

Server addresses: ``MXNET_KVSTORE_SERVER_HOSTS`` (comma list, one per
process) or 127.0.0.1 for single-machine multi-process runs;
``MXNET_KVSTORE_PORT_BASE`` (default 24500) + rank.
"""
from __future__ import annotations

import errno
import logging
import os
import pickle
import random
import socket
import struct
import threading
import time

import numpy as np

from .base import MXNetError
from . import telemetry as tele
from .kvstore import _bigarray_bound  # single source for the threshold

__all__ = ["PSBackend"]

# transport health metrics (doc/observability.md "kvstore_dist"): a
# retry storm, a flapping server or a half-open peer shows up here
# long before the bounded-retry MXNetError does
_TM_PUSHES = tele.counter("kvstore.pushes")
_TM_PULLS = tele.counter("kvstore.pulls")
_TM_PUSH_BYTES = tele.counter("kvstore.push_bytes")
_TM_PULL_BYTES = tele.counter("kvstore.pull_bytes")
_TM_RETRIES = tele.counter("kvstore.retries")
_TM_RECONNECTS = tele.counter("kvstore.reconnects")
_TM_TIMEOUTS = tele.counter("kvstore.timeouts")
_TM_DEDUP_HITS = tele.counter("kvstore.dedup_hits")
_TM_PING_MS = tele.histogram("kvstore.ping_rtt_ms")
_TM_REQUEST_MS = tele.histogram("kvstore.request_ms")

_LEN = struct.Struct("!Q")

# Test seam: ``mxnet_tpu.testing.faults`` installs an injector here to
# deterministically drop/delay/sever CLIENT-side frames (the server side
# is faulted by killing/restarting the _Server itself). None in
# production — the hot path pays one attribute read per request.
_CLIENT_FAULTS = None


def _request_timeout():
    """Per-request socket timeout in seconds (MXNET_KVSTORE_TIMEOUT).

    Generous by default: on oversubscribed test hosts a peer can
    legitimately stall for minutes inside an XLA compile; a DEAD peer is
    detected by TCP reset or the ping probe, not by idleness (ps-lite
    likewise waits on its van)."""
    return float(os.environ.get("MXNET_KVSTORE_TIMEOUT", "600"))


def _max_retries():
    """Resend budget AFTER the first attempt (MXNET_KVSTORE_MAX_RETRIES)."""
    return int(os.environ.get("MXNET_KVSTORE_MAX_RETRIES", "4"))


def _backoff_base_s():
    """Base reconnect backoff in seconds (MXNET_KVSTORE_BACKOFF_MS)."""
    return float(os.environ.get("MXNET_KVSTORE_BACKOFF_MS", "100")) / 1000.0

# SECURITY: the wire format is pickle, and ``pickle.loads`` on attacker
# bytes is remote code execution. Like ps-lite's ZMQ, this transport
# assumes a TRUSTED private cluster network. Set
# ``MXNET_KVSTORE_SECRET`` (any shared string, exported to every
# process — tools/launch.py forwards env) to require an HMAC-SHA256 tag
# on every message, rejecting frames from anything that doesn't hold
# the secret. Do NOT expose the server port beyond the cluster.


def _secret():
    return os.environ.get("MXNET_KVSTORE_SECRET", "").encode()


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sec = _secret()
    if sec:
        import hmac
        payload += hmac.new(sec, payload, "sha256").digest()
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    payload = _recv_exact(sock, n)
    sec = _secret()
    if sec:
        import hmac
        if len(payload) < 32:
            raise MXNetError("kvstore dist_async: short frame under "
                             "MXNET_KVSTORE_SECRET")
        payload, tag = payload[:-32], payload[-32:]
        want = hmac.new(sec, payload, "sha256").digest()
        if not hmac.compare_digest(tag, want):
            raise MXNetError(
                "kvstore dist_async: HMAC verification failed — peer "
                "does not hold MXNET_KVSTORE_SECRET (refusing to "
                "unpickle untrusted bytes)")
    return pickle.loads(payload)


def _port_base():
    if "MXNET_KVSTORE_PORT_BASE" in os.environ:
        return int(os.environ["MXNET_KVSTORE_PORT_BASE"])
    # derive from the coordinator port so concurrent launches on one
    # machine (each with its own free coordinator port) don't collide
    coord = os.environ.get("MXNET_TPU_COORDINATOR")
    if coord and ":" in coord:
        return int(coord.rsplit(":", 1)[1]) + 1000
    return 24500


class _Server(threading.Thread):
    """One server thread: owns a slice of the key space; applies pushes
    immediately (async semantics). Daemon — dies with the process.

    ``predecessor`` hands a dead server's whole state — store, updater,
    retry-dedup table, AND its lock/condition (a predecessor handler
    thread can still be mid-apply when the successor starts; sharing the
    synchronization keeps that late publish visible to successor
    waiters) — to a restart-after-crash successor (or the fault
    harness's kill/restart injector): the analogue of a ps-lite server
    recovering from its replica."""

    def __init__(self, rank, port, predecessor=None):
        super().__init__(daemon=True, name="mxnet-ps-server-%d" % rank)
        self.rank = rank
        self.port = port
        if predecessor is not None:
            self.store = predecessor.store       # (key, part) -> np
            # the updater lives in a SHARED one-slot box, not a
            # per-instance attribute: a predecessor handler finishing a
            # set_optimizer mid-restart must install into the successor
            # too (the shared _dedup acks that request as applied)
            self._updater_box = predecessor._updater_box
            self._dedup = predecessor._dedup
            self._claim_holders = predecessor._claim_holders
            self.lock = predecessor.lock
            self._applied = predecessor._applied
        else:
            self.store = {}
            self._updater_box = {"u": None}
            # client_id -> (seq, reply) of the last MUTATING request
            # for that client: a retried push/init/set_optimizer (reply
            # lost to a connection drop AFTER the server applied it) is
            # answered from here instead of being applied twice —
            # exactly-once updates under at-least-once delivery. One
            # entry per client; reply None marks an in-flight claim
            # whose executing thread is in _claim_holders (see _claim).
            self._dedup = {}
            self._claim_holders = {}
            self.lock = threading.Lock()
            self._applied = threading.Condition(self.lock)
        self.conns = []        # accepted sockets — see close()
        # conns gets its own lock: run() must keep accepting (and
        # spawning handler threads — the ping heartbeat rides one) while
        # a long updater apply holds self.lock, or a merely-slow server
        # would be unreachable for probes and misclassified as dead
        self._conns_lock = threading.Lock()
        self._closed = False   # set by close(), checked under _conns_lock
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self.sock.bind(("0.0.0.0", port))
        except OSError as e:
            raise MXNetError(
                "dist_async: cannot bind parameter-server port %d (%s). "
                "Another job on this host owns it — set "
                "MXNET_KVSTORE_PORT_BASE to a free range." % (port, e))
        self.sock.listen(64)

    @property
    def updater(self):
        return self._updater_box["u"]

    @updater.setter
    def updater(self, fn):
        self._updater_box["u"] = fn

    def run(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return  # socket closed at shutdown
            with self._conns_lock:
                if self._closed:
                    # close() already drained conns: a connection that
                    # slipped through accept() in that window must not
                    # be served — a "killed" server would keep this
                    # socket ESTABLISHED and the port bound, failing
                    # the successor's bind
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                self.conns.append(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             name="mxnet-ps-handler-%d" % self.rank,
                             daemon=True).start()

    def close(self):
        """Close the listener AND every accepted connection: on Linux an
        ESTABLISHED accepted socket still counts as bound to the port,
        so a successor server could not re-bind until they are gone
        (SO_REUSEADDR only covers TIME_WAIT). shutdown() first: close()
        alone does NOT unblock a thread sitting in accept() — the kernel
        keeps the listening socket (and the port!) alive until that
        syscall returns, so a "killed" server would silently keep
        accepting."""
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        with self._conns_lock:
            self._closed = True
            conns, self.conns = self.conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    # ops whose effect on server state is NOT idempotent — only their
    # replies are cached for retry dedup (pull/ping re-execute freely)
    _MUTATING_OPS = ("init", "push", "set_optimizer")

    def _claim(self, client, seq):
        """Atomically claim a mutating request for execution; return the
        cached reply instead when ``(client, seq)`` was already applied.

        The dedup entry is written BEFORE execution as ``(seq, None)`` —
        a claim — so a timeout-resent duplicate arriving while the
        original is still inside the updater blocks here until the first
        handler publishes its reply, instead of racing past a
        not-yet-written cache entry and double-applying the push. A
        waiter takes an unpublished claim over ONLY when its holder
        thread is dead (handler error mid-apply) — re-execution then,
        but only in that pathological case; a merely-slow holder (alive
        inside the updater) is waited on indefinitely."""
        deadline = time.monotonic() + _request_timeout()
        with self.lock:
            while True:
                hit = self._dedup.get(client)
                if hit is not None and hit[0] > seq:
                    # a frame from BEFORE the client's current request
                    # (buffered on a conn the client abandoned, read
                    # late): the client only advances seq after its
                    # previous mutating request was applied, so this is
                    # an already-applied duplicate — ack, never re-run
                    _TM_DEDUP_HITS.inc()
                    return ("ok",)
                if hit is None or hit[0] != seq:
                    self._dedup[client] = (seq, None)  # ours to execute
                    self._claim_holders[client] = \
                        threading.current_thread()
                    return None
                if hit[1] is not None:
                    _TM_DEDUP_HITS.inc()
                    return hit[1]  # duplicate of an applied request
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    holder = self._claim_holders.get(client)
                    if holder is not None and holder.is_alive():
                        # alive but slow (long updater apply): keep
                        # waiting — taking over would double-apply
                        deadline = (time.monotonic()
                                    + _request_timeout())
                        continue
                    self._dedup[client] = (seq, None)  # holder died
                    self._claim_holders[client] = \
                        threading.current_thread()
                    return None
                self._applied.wait(remaining)

    def _serve(self, conn):
        # a half-open worker (crashed without FIN, NAT dropped the flow)
        # must not wedge this handler in _recv_exact forever: after the
        # request timeout of idleness treat the peer as gone and close
        conn.settimeout(_request_timeout())
        try:
            while True:
                msg = _recv_msg(conn)
                client = seq = None
                claimed = False
                if msg[0] == "req":
                    # retry-safe envelope: (op, ...) wrapped with the
                    # sender's identity and a per-client sequence number
                    _, client, seq, msg = msg
                    if msg[0] in self._MUTATING_OPS:
                        cached = self._claim(client, seq)
                        if cached is not None:
                            _send_msg(conn, cached)  # already applied
                            continue
                        claimed = True
                try:
                    reply = self._handle(msg)
                except BaseException:
                    if claimed:
                        # publish an err reply so the client's retry
                        # fails FAST: an unpublished claim would stall
                        # every resend a full request timeout inside
                        # _claim before dead-holder takeover, then
                        # re-execute and fail again — with defaults
                        # that is minutes of hang for a deterministic
                        # server-side apply error
                        err = ("err", "server-side apply failed "
                               "(see server %d log)" % self.rank)
                        with self.lock:
                            hit = self._dedup.get(client)
                            if hit is not None and hit[0] == seq:
                                # only publish onto OUR claim: a newer
                                # request may have claimed after our
                                # client gave up on this one
                                self._dedup[client] = (seq, err)
                                self._claim_holders.pop(client, None)
                            self._applied.notify_all()
                        try:
                            # best effort on the live conn too, so the
                            # FIRST attempt sees the error without
                            # paying a reconnect + backoff round
                            _send_msg(conn, err)
                        except OSError:
                            pass
                    raise
                if claimed:
                    with self.lock:
                        hit = self._dedup.get(client)
                        if hit is not None and hit[0] == seq:
                            # only publish onto OUR claim: if the
                            # client gave up on this seq (retry budget
                            # spent while we were inside a long apply)
                            # and moved on, a newer request owns the
                            # slot — rolling it back would reopen the
                            # double-apply window
                            self._dedup[client] = (seq, reply)
                            self._claim_holders.pop(client, None)
                        self._applied.notify_all()
                _send_msg(conn, reply)
                if msg[0] == "stop":
                    return
        except socket.timeout:
            logging.warning(
                "parameter server %d: peer idle beyond "
                "MXNET_KVSTORE_TIMEOUT=%ss — assuming half-open "
                "connection and dropping it", self.rank,
                _request_timeout())
        except (ConnectionError, EOFError):
            pass
        except OSError as e:
            # EBADF only: close() pulled this connection out from under
            # a blocked recv (server shutdown) — expected, not a crash.
            # Any other OSError (e.g. an updater hitting a full disk
            # mid-apply) is a real handler failure and must be loud.
            if e.errno != errno.EBADF:
                import traceback
                logging.error("parameter server %d: handler crashed:\n%s",
                              self.rank, traceback.format_exc())
        except BaseException:
            # a dying server thread must not be silent: the peer only
            # sees a connection reset with no cause
            import traceback
            logging.error("parameter server %d: handler crashed:\n%s",
                          self.rank, traceback.format_exc())
        finally:
            conn.close()
            with self._conns_lock:
                # drop the dead socket from the close() bookkeeping or
                # conns grows by one entry per ping probe / reconnect
                # for the life of the server
                try:
                    self.conns.remove(conn)
                except ValueError:
                    pass  # close() already drained the list

    def _handle(self, msg):
        """Apply one request; return the reply tuple."""
        op = msg[0]
        if op == "init":
            _, key, part, val = msg
            with self.lock:
                # first init wins (every worker inits every key)
                self.store.setdefault((key, part), val.copy())
            return ("ok",)
        elif op == "push":
            _, key, part, val = msg
            with self.lock:
                if (key, part) not in self.store:
                    return ("err", "key %s not init" % key)
                stored = self.store[(key, part)]
                if self.updater is not None:
                    # update-per-push, reference
                    # kvstore_dist_server.h:194-202
                    from . import ndarray as nd
                    recv = nd.array(val)
                    dst = nd.array(stored)
                    self.updater(key, recv, dst)
                    self.store[(key, part)] = dst.asnumpy()
                else:
                    # no updater: plain overwrite-with-merged,
                    # like the reference server without optimizer
                    self.store[(key, part)] = val.copy()
            return ("ok",)
        elif op == "pull":
            _, key, part = msg
            with self.lock:
                val = self.store.get((key, part))
            if val is None:
                return ("err", "key %s not init" % key)
            return ("ok", val)
        elif op == "set_optimizer":
            from . import optimizer as opt_mod
            optimizer = pickle.loads(msg[1])
            with self.lock:
                if isinstance(optimizer, opt_mod.Optimizer):
                    self.updater = opt_mod.get_updater(optimizer)
                else:
                    self.updater = optimizer  # pre-built updater
            return ("ok",)
        elif op == "ping":
            # heartbeat: lets a worker distinguish a dead server
            # (connect refused / reset) from a slow one (ping answers
            # while a long request is still being chewed on)
            return ("ok", "pong")
        elif op == "stop":
            return ("ok",)
        return ("err", "bad op %r" % (op,))


class PSBackend:
    """Worker-side client + this process's colocated server.

    One live backend per process (like one ps-lite van per process):
    creating a new dist_async store closes the previous backend's
    sockets first — GC cannot be relied on to run ``close()`` before
    the new server binds the same port, because the server THREAD
    object stays registered in ``threading`` while its accept loop
    runs. Sequential store lifetimes only; two concurrently-used
    dist_async stores in one process are not supported (they weren't
    in the reference either — one ps-lite customer id per role).
    """

    _live = None
    _generation = 0

    def __init__(self):
        import jax
        if PSBackend._live is not None:
            PSBackend._live.close()
            PSBackend._live = None
        # each store generation gets a fresh port block: even after
        # close(), peer-held FIN_WAIT sockets keep the OLD ports bound
        # on Linux, so re-binding them is not reliable. Store creation
        # is collective (every process creates stores in the same
        # order), so the generation — and thus the port map — agrees
        # across processes without communication.
        PSBackend._generation += 1
        self.generation = PSBackend._generation
        self.rank = jax.process_index()
        self.nserv = jax.process_count()
        hosts = os.environ.get("MXNET_KVSTORE_SERVER_HOSTS")
        if hosts:
            self.hosts = [h.strip() for h in hosts.split(",")]
            if len(self.hosts) != self.nserv:
                raise MXNetError(
                    "MXNET_KVSTORE_SERVER_HOSTS lists %d hosts for %d "
                    "processes" % (len(self.hosts), self.nserv))
        else:
            self.hosts = ["127.0.0.1"] * self.nserv
        self.server = _Server(self.rank, self._port(self.rank))
        self.server.start()
        self._conns = {}
        self._lock = threading.Lock()
        self._layout = {}  # key -> [(server, slice)] fixed at init
        # retry-safe identity: servers dedup mutating requests by
        # (client_id, seq), so a retried push is applied exactly once
        self._client_id = "w%d.g%d.%08x" % (
            self.rank, self.generation,
            int.from_bytes(os.urandom(4), "little"))
        self._seq = 0
        # make sure every server is listening before anyone pushes
        from . import distributed
        distributed.barrier("ps_backend_up")
        PSBackend._live = self
        logging.info("dist_async parameter server up: rank %d/%d",
                     self.rank, self.nserv)

    def _port(self, server):
        return _port_base() + (self.generation - 1) * self.nserv + server

    # -- transport ----------------------------------------------------
    def _conn_locked(self, server):
        c = self._conns.get(server)
        if c is None:
            c = socket.create_connection(
                (self.hosts[server], self._port(server)),
                timeout=_request_timeout())
            self._conns[server] = c
        return c

    def _drop_conn_locked(self, server):
        stale = self._conns.pop(server, None)
        if stale is not None:
            _TM_RECONNECTS.inc()  # next _conn_locked dials fresh
            try:
                stale.close()
            except OSError:
                pass

    def _ping(self, server, timeout=None):
        """Heartbeat probe on a FRESH short-timeout connection: True iff
        the server's accept loop answers. Distinguishes a dead server
        (connect refused/reset -> False) from one that is alive but slow
        on a long request (the probe rides its own handler thread)."""
        if timeout is None:
            timeout = min(5.0, _request_timeout())
        try:
            tic = time.perf_counter()
            with socket.create_connection(
                    (self.hosts[server], self._port(server)),
                    timeout=timeout) as c:
                _send_msg(c, ("ping",))
                ok = _recv_msg(c)[0] == "ok"
            if ok:
                _TM_PING_MS.observe((time.perf_counter() - tic) * 1e3)
            return ok
        except (OSError, EOFError, MXNetError):
            return False

    def _request(self, server, msg):
        """One request/reply round trip, with bounded retries.

        Failure policy (reference ps-lite resent its van messages after
        ZMQ reconnected; this is the same contract over raw TCP):

        * connection drop/refusal -> reconnect and resend with
          exponential backoff + jitter, up to MXNET_KVSTORE_MAX_RETRIES
          times (a server restarting behind the same port is picked
          back up transparently);
        * request timeout -> ping-probe the server on a side
          connection: alive means slow (resend, the dedup layer makes
          that safe), dead means the backoff path;
        * budget exhausted -> a loud MXNetError naming the peer and
          whether it looked dead or merely slow, so the operator can
          act (restart from the last checkpoint vs raise the timeout).

        Mutating requests carry (client_id, seq) so a server that
        already applied a retried push answers from its dedup cache
        instead of double-applying (see _Server._serve).
        """
        retries = _max_retries()
        backoff = _backoff_base_s()
        req_t0 = time.perf_counter()
        with self._lock:  # one in-flight request per worker (like the
            self._seq += 1  # engine var serializing pushes)
            envelope = ("req", self._client_id, self._seq, msg)
            last_err, server_alive = None, False
            for attempt in range(retries + 1):
                try:
                    c = self._conn_locked(server)
                    do_send = True
                    if _CLIENT_FAULTS is not None:
                        do_send = _CLIENT_FAULTS.before_send(
                            server, envelope, c)
                    if do_send:
                        _send_msg(c, envelope)
                    reply = _recv_msg(c)
                    if _CLIENT_FAULTS is not None:
                        _CLIENT_FAULTS.after_recv(
                            server, envelope, reply, c)
                    break
                except (ConnectionError, socket.timeout, OSError) as e:
                    last_err = e
                    if isinstance(e, socket.timeout):
                        _TM_TIMEOUTS.inc()
                    if attempt < retries:
                        _TM_RETRIES.inc()  # about to resend
                    self._drop_conn_locked(server)
                    # a timeout on an ESTABLISHED connection may just be
                    # a slow server: the heartbeat tells us which
                    server_alive = (isinstance(e, socket.timeout)
                                    and self._ping(server))
                    if attempt >= retries:
                        self._raise_dead(server, attempt + 1,
                                         server_alive, e)
                    if not server_alive:
                        # cap AFTER the jitter multiply: the documented
                        # bound is 10s per sleep, jitter included
                        delay = backoff * (2 ** attempt)
                        time.sleep(min(delay * (0.5 + random.random()),
                                       10.0))
            else:  # pragma: no cover - loop always breaks or raises
                self._raise_dead(server, retries + 1, False, last_err)
        _TM_REQUEST_MS.observe((time.perf_counter() - req_t0) * 1e3)
        if reply[0] != "ok":
            raise MXNetError("parameter server: %s" % (reply[1],))
        return reply[1] if len(reply) > 1 else None

    def _raise_dead(self, server, attempts, alive, err):
        # a dead/unreachable server is a cluster failure, not a bug in
        # the caller: name the peer so the operator can act (the
        # reference's ps-lite likewise aborts the run when a server van
        # connection drops)
        if alive:
            state = ("is alive (heartbeat answers) but did not reply "
                     "within MXNET_KVSTORE_TIMEOUT=%ss" %
                     _request_timeout())
        else:
            state = "is unreachable or died mid-request"
        raise MXNetError(
            "dist_async: parameter server %d (%s:%d) %s after %d "
            "attempt(s) (%s: %s). The key range it owned is lost; "
            "restart the job from the last checkpoint."
            % (server, self.hosts[server], self._port(server), state,
               attempts, type(err).__name__, err))

    # -- key placement (reference EncodeKey, kvstore_dist.h:230-268) --
    def _owner(self, key):
        return (key * 9973) % self.nserv

    def _partition(self, key, shape):
        """[(server, slice)] — whole-array for small keys, first-axis
        ranges across every server for big ones."""
        size = int(np.prod(shape)) if shape else 1
        if size < _bigarray_bound() or not shape or shape[0] < self.nserv:
            return [(self._owner(key), slice(None))]
        rows = shape[0]
        per = -(-rows // self.nserv)
        parts = []
        for s in range(self.nserv):
            lo = min(s * per, rows)
            hi = min(lo + per, rows)
            if lo < hi:
                parts.append((s, slice(lo, hi)))
        return parts

    # -- API ----------------------------------------------------------
    def init(self, key, value):
        value = np.asarray(value)
        self._layout[key] = self._partition(key, value.shape)
        for part, (server, sl) in enumerate(self._layout[key]):
            self._request(server, ("init", key, part, value[sl]))

    def push(self, key, value):
        value = np.asarray(value)
        for part, (server, sl) in enumerate(self._layout[key]):
            self._request(server, ("push", key, part, value[sl]))
        # counted after the part loop, like pull: both op/byte counters
        # mean COMPLETED operations (a push that exhausts its retries
        # raises without being counted)
        _TM_PUSHES.inc()
        _TM_PUSH_BYTES.inc(value.nbytes)

    def pull(self, key):
        parts = [self._request(server, ("pull", key, part))
                 for part, (server, _) in enumerate(self._layout[key])]
        _TM_PULLS.inc()
        _TM_PULL_BYTES.inc(sum(p.nbytes for p in parts))
        if len(parts) == 1:
            return parts[0]
        return np.concatenate(parts, axis=0)

    def set_optimizer(self, pickled):
        for s in range(self.nserv):
            self._request(s, ("set_optimizer", pickled))

    def close(self):
        """Finalize the parameter-server backend (reference ps-lite
        Postoffice::Finalize semantics): BARRIER FIRST, then close
        sockets. The barrier must come before ANY server shard goes
        away — a worker that finishes early and tears down its server
        while a slow peer is still pulling kills that peer with a
        connection reset (observed as the 1-core 4-worker flake: ranks
        1-3 GC'd their kvstore while rank 0 was mid-pull on the key
        range rank 2's server owned). Idempotent: only the first close
        barriers and closes, so a second close can never deadlock
        waiting for peers that already left."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        try:
            from jax.experimental import multihost_utils
            # If a peer DIED before reaching this barrier, the jax
            # coordination service detects the missing heartbeat and
            # aborts the collective (it does not hang forever) — the
            # same unhappy-path contract as ps-lite's Finalize barrier.
            multihost_utils.sync_global_devices("kvstore_ps_close")
        except Exception:
            pass  # interpreter teardown / single process: best effort
        with self._lock:
            for c in self._conns.values():
                try:
                    c.close()
                except OSError:
                    pass
            self._conns.clear()
        self.server.close()
        if PSBackend._live is self:
            PSBackend._live = None
