"""KVStore: the data-parallel gradient aggregation API.

Parity: ``/root/reference/python/mxnet/kvstore.py`` +
``include/mxnet/kvstore.h`` (Init/Push/Pull with int or list keys,
aggregation across device copies, pluggable updater, node-role predicates)
and the C++ backends ``src/kvstore/kvstore_local.h`` (pinned-host reduce),
``kvstore_device.h`` (GPU reduce) and ``kvstore_dist.h`` (ps-lite).

TPU-first design
----------------
The reference moves gradients through hand-written reductions (OMP CPU
loops, GPU ElementwiseSum P2P) and a ZMQ parameter server. On TPU the
fast path is *in-program*: the fused data-parallel train step (see
``mxnet_tpu/parallel``) shards the batch over a ``jax.sharding.Mesh`` and
lets XLA insert ``psum`` over ICI — no KVStore object in the loop at all.

This module keeps the KVStore *API* as a compatibility facade:

* ``local``/``device`` (and the ``local_allreduce_*`` aliases): aggregation
  of per-device NDArray copies inside one process. The reduce is a single
  jnp tree-sum — XLA's fusion replaces kvstore_local.h's chunked OMP loops.
* ``dist_sync``: same BSP semantics over multiple processes, but the
  cross-process reduce is an IN-PROGRAM XLA all-reduce over DCN: each
  process contributes its locally-merged gradient as shards of one global
  array on the global device mesh and a jitted sum replaces ps-lite's
  ZPush/ZPull round trip. Arrays >= ``MXNET_KVSTORE_BIGARRAY_BOUND``
  (1e6 elements, the reference's bound) come back REDUCE-SCATTERED: the
  stored value stays sharded across the mesh (the analogue of the
  reference's range partitioning across servers,
  ``kvstore_dist.h:230-268``) and ``pull`` all-gathers on demand.
* ``dist_async``: a real host-driven parameter server
  (``kvstore_dist.py``): one server thread per process, update-per-push
  with no worker lockstep (reference ``kvstore_dist_server.h:194-202``),
  key-hash ownership plus range partitioning for big arrays. Collectives
  are inherently synchronous, so async rides TCP like ps-lite rode ZMQ.
* ``_set_updater``: weight update runs where the reference's "update on
  kvstore" runs (sync: on the aggregated value before broadcast; async:
  inside the owning server thread).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray
from . import optimizer as opt

__all__ = ["KVStore", "create"]


def _bigarray_bound():
    return int(float(os.environ.get("MXNET_KVSTORE_BIGARRAY_BOUND", 1e6)))


def _ctype_key_value(key, vals):
    """Normalize (key, values) to (list[int], list[list[NDArray]])."""
    if isinstance(key, (int, np.integer)):
        key = [int(key)]
        vals = [vals]
    else:
        key = [int(k) for k in key]
    norm = []
    for v in vals:
        if isinstance(v, NDArray):
            norm.append([v])
        else:
            norm.append(list(v))
    return key, norm


class KVStore:
    """In-process key→NDArray store with aggregation semantics."""

    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._sharded = {}  # key -> _ShardedValue (big-array sync path)
        self._updater = None
        self._updater_key = None  # private rank-synced stream, see _call_updater
        self._is_dist = kv_type.startswith("dist")
        self._is_async = kv_type == "dist_async"
        self._ps = None
        if self._is_dist:
            from . import distributed
            distributed.initialize()  # no-op if single-process/already up
        if self._is_async and _num_processes() > 1:
            # real update-per-push parameter server (host-driven over TCP,
            # like the reference's ps-lite over ZMQ): collectives are
            # synchronous by construction, so async cannot ride them
            from .kvstore_dist import PSBackend
            self._ps = PSBackend()

    # ------------------------------------------------------------------
    def init(self, key, value):
        """Initialize key(s) once (reference kvstore.py init).

        dist semantics: the reference's server holds ONE copy of every
        key (first init wins), so after init all workers PULL the same
        value even though each passed its own (differently-seeded)
        initial weights. Here the sync store is per-process, so init
        broadcasts rank 0's value — without this, workers start from
        different params and BSP updates preserve the skew forever.
        """
        key, vals = _ctype_key_value(key, value)
        for k in key:
            if k in self._store:
                raise MXNetError("key %d already initialized" % k)
        sync_bcast = (self._ps is None and self._is_dist
                      and _num_processes() > 1)
        if sync_bcast:
            # ONE pytree broadcast for the whole call (per-key
            # collectives cost a cross-process round trip each — minutes
            # at hundreds of params over a slow DCN link)
            from jax.experimental import multihost_utils
            host_vals = multihost_utils.broadcast_one_to_all(
                [vlist[0].asnumpy() for vlist in vals])
            for k, vlist, val in zip(key, vals, host_vals):
                self._store[k] = nd.array(np.asarray(val),
                                          ctx=vlist[0].context)
            return
        for k, vlist in zip(key, vals):
            v = vlist[0]
            self._store[k] = v.copyto(v.context)
            if self._ps is not None:
                self._ps.init(k, v.asnumpy())

    def _merge_local(self, k, vlist):
        """Sum this process's device copies ON THE STORE'S DEVICE
        (reference kvstore_local.h MergePushValue: per-device grads into
        pinned merge buffers) — the updater then mixes merged and stored
        values without committed-device conflicts."""
        import jax
        dev = self._store[k].context.jax_device()
        merged = jax.device_put(vlist[0]._val, dev)
        for v in vlist[1:]:
            merged = merged + jax.device_put(v._val, dev)
        return merged

    def push(self, key, value, priority=0):
        """Push value(s); multiple device copies of one key are summed
        (reference kvstore_local.h MergePushValue). With an updater set,
        the aggregate is applied via updater(key, merged, stored) instead
        of overwriting — matching reference local-update semantics.

        dist_sync: the cross-process reduce is one in-program XLA
        all-reduce; big arrays come back reduce-scattered (see
        ``_allreduce_dcn``). dist_async: the merged gradient goes to the
        key's owning server, which applies its updater immediately — no
        worker lockstep (reference kvstore_dist_server.h:194-202).
        """
        key, vals = _ctype_key_value(key, value)
        for k, vlist in zip(key, vals):
            if k not in self._store:
                raise MXNetError("key %d not initialized" % k)
            merged = self._merge_local(k, vlist)
            if self._ps is not None:
                self._ps.push(k, np.asarray(merged))
                continue
            if self._is_dist and _num_processes() > 1:
                # updater path needs the full value on every process;
                # pure-aggregation big arrays stay reduce-scattered
                red = _allreduce_dcn(merged,
                                     shard_big=self._updater is None)
                if isinstance(red, _ShardedValue):
                    self._sharded[k] = red
                    continue
                import jax
                pending = self._sharded.pop(k, None)
                if pending is not None:
                    # an updater was installed after a big-array push:
                    # fold the still-sharded aggregate into the store
                    # first (reference overwrite semantics) so it isn't
                    # silently dropped
                    self._store[k]._set(jax.device_put(
                        pending.gather(),
                        self._store[k].context.jax_device()))
                merged = jax.device_put(
                    red, self._store[k].context.jax_device())
            merged_nd = NDArray._from_jax(merged, self._store[k].context)
            if self._updater is not None:
                self._call_updater(k, merged_nd, self._store[k])
            else:
                self._store[k]._set(merged)

    def _call_updater(self, k, recv, local):
        """Run the updater under its PRIVATE rank-synced RNG stream (if
        one was established at _set_updater time).  The global
        ``mx.random`` key is swapped out for the duration of the call
        and restored afterwards, so updater-internal draws (SGLD noise)
        are identical on every process — the BSP invariant — while user
        streams (dropout, augmentation) keep their per-process state."""
        if self._updater_key is None:
            self._updater(k, recv, local)
            return
        from . import random as mx_random
        user_key = mx_random._KEY
        mx_random._KEY = self._updater_key
        try:
            self._updater(k, recv, local)
        finally:
            self._updater_key = mx_random._KEY
            mx_random._KEY = user_key

    def pull(self, key, out=None, priority=0):
        """Pull current value into out array(s) — broadcast to all device
        copies (reference kvstore_local.h Pull → CopyFromTo fan-out).
        Reduce-scattered big arrays are all-gathered here (in-program);
        async keys are fetched from their owning servers."""
        assert out is not None
        key, outs = _ctype_key_value(key, out)
        for k, olist in zip(key, outs):
            if k not in self._store:
                raise MXNetError("key %d not initialized" % k)
            import jax
            if self._ps is not None:
                val = self._ps.pull(k)
                for o in olist:
                    o._set(jax.device_put(val, o.context.jax_device()))
                continue
            if k in self._sharded:
                full = self._sharded[k].gather()
                self._store[k]._set(jax.device_put(
                    full, self._store[k].context.jax_device()))
                del self._sharded[k]
            src = self._store[k]
            for o in olist:
                o._set(jax.device_put(src._val, o.context.jax_device()))

    # ------------------------------------------------------------------
    def _set_updater(self, updater):
        """Install updater(key, recv, local) (reference _set_updater).
        In dist_async mode the updater runs inside the owning SERVER
        thread (reference: servers apply updates), so it must be
        picklable (a module-level function or an Optimizer-based
        updater). Like the reference (rank 0 sends the pickled optimizer,
        command 0), only rank 0 installs it — otherwise a slow worker's
        late set would REPLACE the updater and silently zero optimizer
        state accumulated from earlier pushes; the barrier guarantees
        it is installed before anyone returns."""
        if self._ps is not None:
            if self.rank == 0:
                self._ps.set_optimizer(pickle.dumps(updater))
            self.barrier()
            return
        if self._is_dist and _num_processes() > 1:
            self._sync_rng()
        self._updater = updater

    set_updater = _set_updater

    def _sync_rng(self):
        """dist_sync applies the updater independently on every process's
        replica of the store, so an updater that draws from the global
        ``mx.random`` stream (e.g. SGLD's noise) must draw IDENTICAL
        values everywhere or the replicas silently diverge, breaking the
        BSP identical-params invariant. Establish a PRIVATE updater key
        from a seed drawn on RANK 0 and broadcast: with the same starting
        key and the same (key, order) push sequence under BSP, every
        process's updater-visible stream stays in lockstep — the same
        fix as the sp trainer's replicated fwd rng. The key is swapped
        in only around updater calls (_call_updater), so user-visible
        streams (dropout, augmentation draws) keep their independent
        per-process state, and deriving the seed from rank 0's mx.random
        stream keeps user-requested determinism after mx.random.seed(42)
        without touching any process's numpy state."""
        import jax
        from . import random as mx_random
        seed = np.zeros((1,), np.int64)
        if self.rank == 0:
            seed[0] = int(jax.random.randint(
                mx_random._next_key(), (), 0, 2 ** 31 - 1))
        shared = _allreduce_dcn(seed, shard_big=False)
        self._updater_key = jax.random.PRNGKey(int(np.asarray(shared)[0]))

    def set_optimizer(self, optimizer):
        """Use an optimizer as the updater. In dist mode the reference
        pickles the optimizer to server processes (kvstore.py →
        kvstore_server.py:36-40) — mirrored here to keep the same
        serializability contract; local mode uses the object directly like
        the reference's local path."""
        if self._is_dist:
            optimizer = pickle.loads(pickle.dumps(optimizer))
        if self._ps is not None:
            if self.rank == 0:  # reference: rank 0 sends, others wait
                self._ps.set_optimizer(pickle.dumps(optimizer))
            self.barrier()
            return
        self._set_updater(opt.get_updater(optimizer))

    # --- node roles (reference kvstore.h:154-178; DMLC_ROLE env) --------
    @property
    def rank(self):
        return _process_index() if self._is_dist else 0

    @property
    def num_workers(self):
        return _num_processes() if self._is_dist else 1

    def barrier(self):
        """Global barrier (reference Postoffice::Barrier)."""
        if self._is_dist and _num_processes() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("kvstore_barrier")

    def send_command_to_servers(self, head, body):
        """No-op in-process (reference SendCommandToServers RPC)."""

    def close(self):
        """Release the async parameter-server sockets (if any), so a new
        dist_async store can bind the ports in the same process."""
        if self._ps is not None:
            self._ps.close()
            self._ps = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _num_processes():
    import jax
    return jax.process_count()


def _process_index():
    import jax
    return jax.process_index()


_dcn_state = {}


def _dcn_mesh():
    """One-axis mesh over EVERY device of every process (cached)."""
    if "mesh" not in _dcn_state:
        import jax
        from jax.sharding import Mesh
        devs = np.array(jax.devices())
        _dcn_state["mesh"] = Mesh(devs, ("dcn",))
    return _dcn_state["mesh"]


def _allreduce_dcn(val, shard_big=True):
    """Cross-process sum as an IN-PROGRAM XLA collective over DCN
    (replaces ps-lite ZPush/ZPull — and the round-1 host
    ``process_allgather`` path, which moved O(nprocs x size) bytes
    through every host's Python heap).

    Each of this process's L local devices contributes ``val / L`` as one
    row of a global ``[n_devices, ...]`` array; a jitted ``sum(axis=0)``
    lowers to one XLA all-reduce (intra-host reduce over ICI/shared
    memory, then DCN). Returns a host ndarray for small values; for big
    values (>= MXNET_KVSTORE_BIGARRAY_BOUND) with ``shard_big`` the
    result stays REDUCE-SCATTERED on the mesh (a jax.Array, stored
    as-is; ``pull`` all-gathers) — the reference's range partitioning
    across servers (``kvstore_dist.h:230-268``) in mesh terms.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _dcn_mesh()
    ndev = mesh.devices.size
    nlocal = len(jax.local_devices())
    x = np.asarray(val)
    big = shard_big and x.size >= _bigarray_bound()
    # Contribute the full value on local row 0 and zeros on the other
    # local rows: the global sum is then exactly the cross-process sum in
    # the INPUT dtype — no x/nlocal pre-division, which would silently
    # promote integer stores to float and round low-precision floats.
    if nlocal == 1:
        rows = x[None]
    else:
        rows = np.zeros((nlocal,) + x.shape, dtype=x.dtype)
        rows[0] = x
    in_sh = NamedSharding(mesh, P("dcn", *([None] * x.ndim)))
    stacked = jax.make_array_from_process_local_data(in_sh, rows)

    key = ("fn", stacked.shape, str(x.dtype), big)
    if key not in _dcn_state:
        if big:
            # pad the leading dim so the reduce-scattered shards divide
            pad_to = -(-x.shape[0] // ndev) * ndev
            out_sh = NamedSharding(mesh, P("dcn", *([None] * (x.ndim - 1))))

            def reduce_fn(a):
                # dtype= pins the accumulator: x64 numpy promotion rules
                # would return int64 for int32 inputs
                s = a.sum(axis=0, dtype=a.dtype)
                if pad_to != s.shape[0]:
                    s = jax.numpy.pad(
                        s, [(0, pad_to - s.shape[0])] +
                        [(0, 0)] * (s.ndim - 1))
                return s
        else:
            out_sh = NamedSharding(mesh, P())

            def reduce_fn(a):
                return a.sum(axis=0, dtype=a.dtype)
        _dcn_state[key] = jax.jit(reduce_fn, out_shardings=out_sh)
    out = _dcn_state[key](stacked)
    if big:
        return _ShardedValue(out, x.shape)
    return np.asarray(out)


class _ShardedValue:
    """A reduce-scattered stored value: lives sharded on the global mesh
    (leading dim padded to the device count); gathered only on pull."""

    def __init__(self, arr, true_shape):
        self.arr = arr
        self.true_shape = tuple(true_shape)

    def gather(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        key = ("gather", self.arr.shape, str(self.arr.dtype))
        if key not in _dcn_state:
            _dcn_state[key] = jax.jit(
                lambda a: a,
                out_shardings=NamedSharding(_dcn_mesh(), P()))
        full = np.asarray(_dcn_state[key](self.arr))
        return full[:self.true_shape[0]].reshape(self.true_shape)


def create(name="local"):
    """Create a KVStore (reference kvstore.py create / kvstore.cc:17-49).

    local / local_update_cpu / local_allreduce_cpu / device /
    local_allreduce_device → in-process aggregation;
    dist / dist_sync / dist_async → multi-process collectives.
    """
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    known = ("local", "local_update_cpu", "local_allreduce_cpu", "device",
             "local_allreduce_device", "dist", "dist_sync", "dist_async")
    if name not in known:
        raise MXNetError("unknown KVStore type %s" % name)
    return KVStore(name)
