"""KVStore: the data-parallel gradient aggregation API.

Parity: ``/root/reference/python/mxnet/kvstore.py`` +
``include/mxnet/kvstore.h`` (Init/Push/Pull with int or list keys,
aggregation across device copies, pluggable updater, node-role predicates)
and the C++ backends ``src/kvstore/kvstore_local.h`` (pinned-host reduce),
``kvstore_device.h`` (GPU reduce) and ``kvstore_dist.h`` (ps-lite).

TPU-first design
----------------
The reference moves gradients through hand-written reductions (OMP CPU
loops, GPU ElementwiseSum P2P) and a ZMQ parameter server. On TPU the
fast path is *in-program*: the fused data-parallel train step (see
``mxnet_tpu/parallel``) shards the batch over a ``jax.sharding.Mesh`` and
lets XLA insert ``psum`` over ICI — no KVStore object in the loop at all.

This module keeps the KVStore *API* as a compatibility facade:

* ``local``/``device`` (and the ``local_allreduce_*`` aliases): aggregation
  of per-device NDArray copies inside one process. The reduce is a single
  jnp tree-sum — XLA's fusion replaces kvstore_local.h's chunked OMP loops.
* ``dist_sync``/``dist_async``: same semantics over jax.distributed
  process groups. On a single process it degrades to local (the way the
  reference's dist kvstore with one worker does); multi-host uses
  ``jax.experimental.multihost_utils`` allreduce over DCN.
* ``_set_updater``: weight update runs where the reference's "update on
  kvstore" runs (here: on the aggregated value before broadcast).
"""
from __future__ import annotations

import pickle

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray
from . import optimizer as opt

__all__ = ["KVStore", "create"]


def _ctype_key_value(key, vals):
    """Normalize (key, values) to (list[int], list[list[NDArray]])."""
    if isinstance(key, (int, np.integer)):
        key = [int(key)]
        vals = [vals]
    else:
        key = [int(k) for k in key]
    norm = []
    for v in vals:
        if isinstance(v, NDArray):
            norm.append([v])
        else:
            norm.append(list(v))
    return key, norm


class KVStore:
    """In-process key→NDArray store with aggregation semantics."""

    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._updater = None
        self._is_dist = kv_type.startswith("dist")
        if self._is_dist:
            from . import distributed
            distributed.initialize()  # no-op if single-process/already up
        # NOTE: dist_async degrades to synchronous collectives here — the
        # reference's async path exists because ps-lite servers can apply
        # updates out of lockstep; with in-program DCN collectives there is
        # no server to be async against.

    # ------------------------------------------------------------------
    def init(self, key, value):
        """Initialize key(s) once (reference kvstore.py init)."""
        key, vals = _ctype_key_value(key, value)
        for k, vlist in zip(key, vals):
            if k in self._store:
                raise MXNetError("key %d already initialized" % k)
            v = vlist[0]
            self._store[k] = v.copyto(v.context)

    def push(self, key, value, priority=0):
        """Push value(s); multiple device copies of one key are summed
        (reference kvstore_local.h MergePushValue). With an updater set,
        the aggregate is applied via updater(key, merged, stored) instead
        of overwriting — matching reference local-update semantics."""
        import jax
        key, vals = _ctype_key_value(key, value)
        for k, vlist in zip(key, vals):
            if k not in self._store:
                raise MXNetError("key %d not initialized" % k)
            # device copies live on different chips: gather to the store's
            # device before reducing (reference kvstore_local.h copies each
            # device grad into pinned host merge buffers)
            dev = self._store[k].context.jax_device()
            merged = jax.device_put(vlist[0]._val, dev)
            for v in vlist[1:]:
                merged = merged + jax.device_put(v._val, dev)
            if self._is_dist and _num_processes() > 1:
                merged = _allreduce_dcn(merged)
            merged_nd = NDArray._from_jax(merged, self._store[k].context)
            if self._updater is not None:
                self._updater(k, merged_nd, self._store[k])
            else:
                self._store[k]._set(merged)

    def pull(self, key, out=None, priority=0):
        """Pull current value into out array(s) — broadcast to all device
        copies (reference kvstore_local.h Pull → CopyFromTo fan-out)."""
        assert out is not None
        key, outs = _ctype_key_value(key, out)
        for k, olist in zip(key, outs):
            if k not in self._store:
                raise MXNetError("key %d not initialized" % k)
            import jax
            src = self._store[k]
            for o in olist:
                o._set(jax.device_put(src._val, o.context.jax_device()))

    # ------------------------------------------------------------------
    def _set_updater(self, updater):
        """Install updater(key, recv, local) (reference _set_updater)."""
        self._updater = updater

    set_updater = _set_updater

    def set_optimizer(self, optimizer):
        """Use an optimizer as the updater. In dist mode the reference
        pickles the optimizer to server processes (kvstore.py →
        kvstore_server.py:36-40) — mirrored here to keep the same
        serializability contract; local mode uses the object directly like
        the reference's local path."""
        if self._is_dist:
            optimizer = pickle.loads(pickle.dumps(optimizer))
        self._set_updater(opt.get_updater(optimizer))

    # --- node roles (reference kvstore.h:154-178; DMLC_ROLE env) --------
    @property
    def rank(self):
        return _process_index() if self._is_dist else 0

    @property
    def num_workers(self):
        return _num_processes() if self._is_dist else 1

    def barrier(self):
        """Global barrier (reference Postoffice::Barrier)."""
        if self._is_dist and _num_processes() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("kvstore_barrier")

    def send_command_to_servers(self, head, body):
        """No-op in-process (reference SendCommandToServers RPC)."""

    def __del__(self):
        pass


def _num_processes():
    import jax
    return jax.process_count()


def _process_index():
    import jax
    return jax.process_index()


def _allreduce_dcn(val):
    """Cross-process sum over DCN (replaces ps-lite ZPush/ZPull).

    Takes the host-value path (process_allgather over numpy) because
    KVStore arrays are per-process host-resident NDArrays, not arrays on a
    shared global mesh — the fused parallel trainer is the in-program path.
    """
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(np.asarray(val)).sum(axis=0)


def create(name="local"):
    """Create a KVStore (reference kvstore.py create / kvstore.cc:17-49).

    local / local_update_cpu / local_allreduce_cpu / device /
    local_allreduce_device → in-process aggregation;
    dist / dist_sync / dist_async → multi-process collectives.
    """
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    known = ("local", "local_update_cpu", "local_allreduce_cpu", "device",
             "local_allreduce_device", "dist", "dist_sync", "dist_async")
    if name not in known:
        raise MXNetError("unknown KVStore type %s" % name)
    return KVStore(name)
