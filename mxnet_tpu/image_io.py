"""ImageRecordIter: the packed-image training data pipeline.

Parity: ``src/io/iter_image_recordio.cc`` (+ augmenter/normalize/batch/
prefetch stages) and its Python-facing kwargs (``mx.io.ImageRecordIter``).
The heavy path runs in the native C++ library (``cpp/image_iter.cc``):
multithreaded JPEG decode + augment + normalize into pinned float batches,
overlapped with device compute — the reference's OMP parser + dmlc
ThreadedIter prefetcher collapsed into one component. A pure-Python
fallback (cv2-based) keeps unbuilt trees working.
"""
from __future__ import annotations

import ctypes
import os
import queue as _queue
import threading
import time as _time

import numpy as np

from .base import MXNetError
from .libinfo import get_lib, check_call
from . import ndarray as nd
from . import telemetry as tele
from .io import DataIter, DataBatch
from . import recordio as rec

# decode-pool metrics (doc/observability.md "IO pipeline"). The
# per-batch decode time is measured WORKER-side and rides the existing
# (epoch, batch, slot, pad) announcement tuple back to the consumer —
# no new shared state; only the consumer process feeds the registry.
_TM_DECODE_MS = tele.histogram("io.decode_batch_ms")
_TM_POOL_WAIT_MS = tele.histogram("io.pool_wait_ms")
_TM_POOL_STARVED = tele.counter("io.pool_starved")
_TM_POOL_BATCHES = tele.counter("io.pool_batches")
_TM_POOL_QDEPTH = tele.gauge("io.pool_queue_depth")

__all__ = ["ImageRecordIter", "device_augment_batch",
           "DeviceAugmentIter"]


_U64 = (1 << 64) - 1


class _LightRNG:
    """Tiny per-record RNG (splitmix64) for the augmentation draws.

    Constructing a numpy RandomState per record costs ~0.2-0.35 ms —
    a fifth of the whole 1.5 ms/img decode budget — where this is ~1 µs.
    Only the two draw kinds the augmenters use exist (numpy-convention
    ``randint`` with exclusive high, ``uniform``); numpy distribution
    parity is NOT required because BOTH engines draw from this stream —
    which is exactly what the byte-identity guarantee rests on."""

    __slots__ = ("_s",)

    def __init__(self, state):
        self._s = state & _U64

    def _next(self):
        self._s = (self._s + 0x9E3779B97F4A7C15) & _U64
        z = self._s
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _U64
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _U64
        return z ^ (z >> 31)

    def randint(self, low, high=None):
        if high is None:
            low, high = 0, low
        return low + self._next() % (high - low)

    def uniform(self, low, high):
        return low + (high - low) * (self._next() / float(1 << 64))


def _record_rng(seed, epoch, pos):
    """Per-record RNG for the augmentation draws (crop/mirror/rotate/HSL),
    keyed by (seed, epoch, position-in-epoch) instead of a sequential
    stream — so record ``pos``'s augmentation is the same no matter
    which worker decodes it (or whether any pool exists at all): the
    foundation of the num_workers byte-identical guarantee."""
    return _LightRNG((seed & 0xffffffff) * 0x9E3779B97F4A7C15
                     + (epoch & 0xffffffff) * 0xBF58476D1CE4E5B9
                     + pos * 0x94D049BB133111EB)


def device_augment_batch(data_u8, key=None, crop_shape=None,
                         rand_crop=False, rand_mirror=False,
                         mean=(0.0, 0.0, 0.0), scale=1.0):
    """The device-side augmentation stage for ``device_augment`` batches.

    Jit-friendly: put this INSIDE the compiled train step. Takes the
    iterator's ``[B, H, W, C]`` uint8 batch, applies (optionally random)
    crop to ``crop_shape=(h, w)``, random horizontal flip, and
    per-channel ``(x - mean) * scale`` normalization, returning the
    ``[B, C, h, w]`` float32 batch the host augmenter would have
    produced — but with the uint8 bytes (4x less infeed traffic) crossing
    to the device and the float work running there (reference analogue:
    iter_normalize.h + image_augmenter.h, moved on-chip). ``key`` is a
    jax PRNG key, required when rand_crop/rand_mirror."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    b, big_h, big_w, c = data_u8.shape
    h, w = crop_shape if crop_shape is not None else (big_h, big_w)
    if (rand_crop or rand_mirror) and key is None:
        raise MXNetError("device_augment_batch: random augmentation "
                         "needs a PRNG key")
    x = data_u8
    if rand_crop and (h < big_h or w < big_w):
        ky, kx, key = jax.random.split(key, 3)
        y0s = jax.random.randint(ky, (b,), 0, big_h - h + 1,
                                 dtype=jnp.int32)
        x0s = jax.random.randint(kx, (b,), 0, big_w - w + 1,
                                 dtype=jnp.int32)
        x = jax.vmap(lambda img, y0, x0: lax.dynamic_slice(
            img, (y0, x0, jnp.int32(0)), (h, w, c)))(x, y0s, x0s)
    elif h < big_h or w < big_w:
        y0 = (big_h - h) // 2
        x0 = (big_w - w) // 2
        x = x[:, y0:y0 + h, x0:x0 + w, :]
    if rand_mirror:
        km, key = jax.random.split(key)
        flip = jax.random.bernoulli(km, 0.5, (b,))
        x = jnp.where(flip[:, None, None, None], x[:, :, ::-1, :], x)
    xf = x.astype(jnp.float32)
    xf = (xf - jnp.asarray(mean, jnp.float32)[:c]) * jnp.float32(scale)
    return jnp.transpose(xf, (0, 3, 1, 2))


class ImageRecordIter(DataIter):
    """Iterate packed image records as normalized NCHW float batches.

    Parameters (reference kwarg names): path_imgrec, data_shape (c,h,w),
    batch_size, label_width, mean_r/g/b, scale, resize (shorter edge),
    rand_crop, rand_mirror, shuffle, seed, num_parts, part_index,
    preprocess_threads, prefetch_buffer, round_batch.

    TPU-era extensions: ``device_augment=True`` emits uint8 HWC batches
    at ``data_shape`` (host does decode+resize+center-crop only; apply
    ``device_augment_batch`` inside the compiled step for random
    crop/flip/normalize — 4x less infeed traffic).
    ``scaled_decode=False`` disables the reduced-DCT JPEG decode
    shortcut (on by default; exact no-op whenever no reduction fits).
    ``num_workers=N`` (default ``MXNET_IO_NUM_WORKERS``, 0) fans decode
    over N pool workers — forked processes by default
    (``worker_mode='thread'`` for debugging), each collating finished
    batches into shared memory with ``queue_depth`` batches buffered
    per worker. Epoch contents are byte-identical to the serial engine
    for any worker count under a fixed seed, a worker crash raises
    instead of hanging, and batches are served from reused slot
    buffers (consume or copy before the next iteration — the same
    contract as ``iter_numpy``). ``path_imgidx`` names the
    MXIndexedRecordIO sidecar so startup reads offsets from the index
    instead of scanning the record file. See doc/io_pipeline.md.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, scale=1.0, resize=0,
                 rand_crop=False, rand_mirror=False, shuffle=False, seed=0,
                 num_parts=1, part_index=0, preprocess_threads=4,
                 prefetch_buffer=4, round_batch=True, data_name="data",
                 label_name="softmax_label", mean_img=None,
                 max_rotate_angle=0, random_h=0, random_s=0, random_l=0,
                 device_augment=False, scaled_decode=True,
                 num_workers=None, worker_mode=None, queue_depth=None,
                 path_imgidx=None):
        super().__init__()
        if len(data_shape) != 3:
            raise MXNetError("data_shape must be (channels, height, width)")
        self.batch_size = batch_size
        self._data_shape = tuple(data_shape)
        self._label_width = label_width
        self._data_name = data_name
        self._label_name = label_name
        self._pad = 0
        self._data = None
        self._label = None
        # device_augment: the host emits uint8 HWC batches at data_shape
        # (decode + resize + CENTER crop only — 4x less infeed traffic,
        # no host float pass); random crop/flip/normalize run inside the
        # compiled step via ``device_augment_batch``. rand_crop /
        # rand_mirror / mean / scale become the DEVICE stage's job.
        self._device_augment = bool(device_augment)
        if num_workers is None:
            num_workers = int(os.environ.get("MXNET_IO_NUM_WORKERS",
                                             "0") or 0)
        if worker_mode is None:
            worker_mode = os.environ.get("MXNET_IO_WORKER_MODE",
                                         "process")
        self._num_workers = int(num_workers)

        # mean-image subtraction (reference iter_normalize.h: load the
        # cached mean file, computing + saving it on first use) and the
        # rotate/HSL augmenters (image_augmenter.h) live in the Python
        # engine; requesting them — or the ``num_workers`` decode pool,
        # whose workers ARE the parallelism the native engine gets from
        # its OMP threads — routes past the native decoder.
        extended = (mean_img is not None or max_rotate_angle or random_h
                    or random_s or random_l or self._num_workers > 0)
        self._lib = None if extended else get_lib()
        if self._lib is not None:
            self.handle = ctypes.c_void_p()
            c, h, w = data_shape
            check_call(self._lib.MXTImRecIterCreateEx(
                ctypes.c_char_p(path_imgrec.encode()),
                ctypes.c_int(batch_size), ctypes.c_int(c), ctypes.c_int(h),
                ctypes.c_int(w), ctypes.c_int(label_width),
                ctypes.c_float(mean_r), ctypes.c_float(mean_g),
                ctypes.c_float(mean_b), ctypes.c_float(scale),
                ctypes.c_int(resize),
                ctypes.c_int(int(rand_crop and not device_augment)),
                ctypes.c_int(int(rand_mirror and not device_augment)),
                ctypes.c_int(int(shuffle)),
                ctypes.c_uint(seed), ctypes.c_int(num_parts),
                ctypes.c_int(part_index), ctypes.c_int(preprocess_threads),
                ctypes.c_int(prefetch_buffer), ctypes.c_int(int(round_batch)),
                ctypes.c_int(int(device_augment)),
                ctypes.c_int(int(scaled_decode)),
                ctypes.byref(self.handle)))
            if device_augment:
                self._buf_data = np.empty((batch_size, h, w, c),
                                          dtype=np.uint8)
            else:
                self._buf_data = np.empty((batch_size,) + self._data_shape,
                                          dtype=np.float32)
            self._buf_label = np.empty((batch_size, label_width),
                                       dtype=np.float32)
        else:
            self.handle = None
            kwargs = dict(mean_img=mean_img,
                          max_rotate_angle=max_rotate_angle,
                          random_h=random_h, random_s=random_s,
                          random_l=random_l,
                          out_uint8=device_augment,
                          scaled_decode=scaled_decode,
                          path_imgidx=path_imgidx)
            args = (path_imgrec, self._data_shape, batch_size,
                    label_width, (mean_r, mean_g, mean_b), scale, resize,
                    rand_crop and not device_augment,
                    rand_mirror and not device_augment, shuffle,
                    seed, num_parts, part_index, round_batch)
            if self._num_workers > 0:
                self._py = _ParallelEngine(
                    *args, num_workers=self._num_workers,
                    worker_mode=worker_mode, queue_depth=queue_depth,
                    **kwargs)
            else:
                self._py = _PyEngine(*args, **kwargs)

    @property
    def provide_data(self):
        if self._device_augment:
            c, h, w = self._data_shape
            return [(self._data_name, (self.batch_size, h, w, c))]
        return [(self._data_name, (self.batch_size,) + self._data_shape)]

    @property
    def provide_label(self):
        return [(self._label_name,
                 (self.batch_size,)
                 if self._label_width == 1
                 else (self.batch_size, self._label_width))]

    def reset(self):
        if self._lib is not None:
            check_call(self._lib.MXTImRecIterReset(self.handle))
        else:
            self._py.reset()

    def _native_next(self):
        """One native-iterator step into the reused buffers; returns
        (has_batch, pad). Shared by iter_next and iter_numpy."""
        has = ctypes.c_int()
        pad = ctypes.c_int()
        if self._device_augment:
            check_call(self._lib.MXTImRecIterNextU8(
                self.handle,
                self._buf_data.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_uint8)),
                self._buf_label.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_float)),
                ctypes.byref(pad), ctypes.byref(has)))
        else:
            check_call(self._lib.MXTImRecIterNext(
                self.handle,
                self._buf_data.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_float)),
                self._buf_label.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_float)),
                ctypes.byref(pad), ctypes.byref(has)))
        return bool(has.value), pad.value

    def iter_next(self):
        if self._lib is not None:
            has, pad = self._native_next()
            if not has:
                return False
            self._pad = pad
            data, label = self._buf_data, self._buf_label
            reused = True
        else:
            got = self._py.next()
            if got is None:
                return False
            data, label, self._pad = got
            reused = getattr(self._py, "reuses_buffers", False)
        if self._label_width == 1:
            label = label.reshape(self.batch_size)
        if reused:
            # the DataBatch protocol hands out long-lived arrays, but
            # jnp.asarray can alias page-aligned host memory ZERO-COPY
            # on the cpu backend — wrapping a reused decode buffer
            # (native double buffer, pool shm slot) uncopied would let
            # later batches mutate earlier ones under the consumer.
            # iter_numpy stays zero-copy with its documented contract.
            data = np.array(data)
            label = np.array(label)
        self._data = nd.array(data)
        self._label = nd.array(label)
        return True

    def iter_numpy(self):
        """Yield (data, label, pad) as NUMPY arrays — the zero-copy-ish
        fast path for host-side consumers (``trainer.prefetch`` feeds
        host numpy dicts; wrapping every batch in device NDArrays would
        cost a device transfer per batch for nothing). Buffers are
        reused: consume or copy before the next iteration."""
        if self._lib is None:
            while True:
                got = self._py.next()
                if got is None:
                    return
                yield got
        while True:
            has, pad = self._native_next()
            if not has:
                return
            yield self._buf_data, self._buf_label, pad

    def getdata(self):
        return [self._data]

    def getlabel(self):
        return [self._label]

    def getpad(self):
        return self._pad

    def close(self):
        """Release the native handle / shut down the decode-worker pool
        (joined and reaped — no stray processes). Idempotent; also runs
        from ``__del__``."""
        if getattr(self, "_lib", None) is not None and self.handle:
            try:
                self._lib.MXTImRecIterFree(self.handle)
            except Exception:
                pass
            self.handle = None
        py = getattr(self, "_py", None)
        if py is not None and hasattr(py, "close"):
            py.close()

    def __del__(self):
        self.close()


class _PyEngine:
    """cv2-based fallback with identical semantics (single-threaded).

    Also the decode kernel of the ``num_workers`` pool: each pool worker
    constructs one of these with pre-sharded ``offsets`` (and the
    parent's ``mean_arr``) and drives ``load_batch`` directly — the
    per-record RNG (``_record_rng``) makes any batch reproducible from
    (seed, epoch, batch index) alone, with no sequential state."""

    def __init__(self, path, data_shape, batch_size, label_width, means,
                 scale, resize, rand_crop, rand_mirror, shuffle, seed,
                 num_parts, part_index, round_batch, mean_img=None,
                 max_rotate_angle=0, random_h=0, random_s=0, random_l=0,
                 out_uint8=False, scaled_decode=True, path_imgidx=None,
                 offsets=None, mean_arr=None):
        import cv2  # noqa: F401  (validates availability early)
        self.out_uint8 = out_uint8
        self.scaled_decode = scaled_decode
        self.path = path
        self.data_shape = data_shape
        self.batch_size = batch_size
        self.label_width = label_width
        self.means = np.array(means, np.float32)
        self.scale = scale
        self.resize = resize
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.shuffle = shuffle
        self.seed = seed
        self.round_batch = round_batch
        self.max_rotate_angle = max_rotate_angle
        self.random_h = random_h
        self.random_s = random_s
        self.random_l = random_l
        self.mean_arr = mean_arr
        self._mean_img_path = mean_img
        self.part_index = part_index
        if offsets is not None:
            # pool worker: the parent already scanned and sharded
            self._all_offsets = list(offsets)
            self.offsets = list(offsets)
        else:
            # offsets once, via the .idx sidecar when one exists
            all_offsets = rec.list_record_offsets(path, path_imgidx)
            self._all_offsets = all_offsets  # mean-img is global
            self.offsets = all_offsets[part_index::num_parts]
        if not self.offsets:
            raise MXNetError("empty shard")
        self.epoch = 0
        self.reset()
        if mean_img is not None and mean_arr is None:
            self._setup_mean_img(mean_img)

    def _setup_mean_img(self, path):
        """Load the (c,h,w) mean image, computing and caching it on first
        use like the reference (iter_normalize.h: compute over the dataset
        with augmentation off, save, then subtract per sample).

        Under ``num_parts>1`` only part 0 computes (the mean is over ALL
        records — decoding the whole dataset once, not once per worker);
        other parts wait for the cache file to appear."""
        import os
        import time as _time
        if self.part_index != 0 and not os.path.exists(path):
            deadline = _time.time() + float(
                os.environ.get("MXNET_MEAN_IMG_TIMEOUT", 600))
            while not os.path.exists(path):
                if _time.time() > deadline:
                    break  # fall through: compute locally (same result)
                _time.sleep(0.2)
        from . import ndarray as _nd
        if os.path.exists(path):
            loaded = _nd.load(path)
            arr = (loaded.get("mean_img") if isinstance(loaded, dict)
                   else loaded[0])
            self.mean_arr = arr.asnumpy().astype(np.float32)
            return
        # compute over RAW pixels: augmentation off AND scalar
        # normalization off, else the cached mean would bake in
        # mean_r/g/b and scale (reference computes over raw images)
        saved = (self.rand_crop, self.rand_mirror, self.max_rotate_angle,
                 self.random_h, self.random_s, self.random_l, self.means,
                 self.scale)
        self.rand_crop = self.rand_mirror = False
        self.max_rotate_angle = self.random_h = self.random_s = \
            self.random_l = 0
        self.means = np.zeros(3, np.float32)
        self.scale = 1.0
        # mean over ALL records, not this worker's num_parts shard —
        # every worker must subtract the SAME mean or distributed runs
        # silently train on inconsistently normalized data
        total = np.zeros(self.data_shape, np.float64)
        count = 0
        dummy_rng = _record_rng(0, 0, 0)  # augmentation is off: no draws
        for off in self._all_offsets:
            img, _ = self._load(off, dummy_rng)
            total += img
            count += 1
        self.mean_arr = (total / max(count, 1)).astype(np.float32)
        # atomic cache write: workers may race on a shared filesystem;
        # tmp (unique per pid) + os.replace means readers only ever see
        # a complete file, last writer wins with identical content
        tmp = "%s.tmp.%d" % (path, os.getpid())
        _nd.save(tmp, {"mean_img": _nd.array(self.mean_arr)})
        os.replace(tmp, path)
        (self.rand_crop, self.rand_mirror, self.max_rotate_angle,
         self.random_h, self.random_s, self.random_l, self.means,
         self.scale) = saved
        # rewind the epoch counter so cold-cache (mean computed) and
        # warm-cache (mean loaded) runs see identical shuffle/RNG streams
        self.epoch -= 1
        self.reset()

    def order_for(self, epoch):
        """Epoch ``epoch``'s record order: the shard's offsets, shuffled
        under the (seed, epoch) stream. Pure function of its arguments —
        the pool workers and the consumer derive identical orders from
        the epoch number alone."""
        order = list(self.offsets)
        if self.shuffle:
            rng = np.random.RandomState(
                ((self.seed << 10) + epoch) & 0xffffffff)
            rng.shuffle(order)
        return order

    def num_batches(self):
        """Batches per epoch (the final partial batch is served padded
        under round_batch, dropped otherwise)."""
        full, rem = divmod(len(self.offsets), self.batch_size)
        return full + (1 if rem and self.round_batch else 0)

    def reset(self):
        self.cur_epoch = self.epoch
        self.order = self.order_for(self.cur_epoch)
        self.cursor = 0
        self.epoch += 1
        self.reader = rec.MXRecordIO(self.path, "r")

    def _header_label(self, header):
        label = np.zeros(self.label_width, np.float32)
        lab = header.label
        if isinstance(lab, np.ndarray):
            label[:min(self.label_width, lab.size)] = lab[:self.label_width]
        else:
            label[0] = lab
        return label

    @staticmethod
    def _probe_size(blob):
        """(rows, cols) from JPEG SOF / PNG IHDR header bytes (the
        Python port of cpp/image_iter.cc ProbeImageSize) — no decode."""
        d = blob
        n = len(d)
        if n >= 24 and d[:4] == b"\x89PNG":
            cols = int.from_bytes(d[16:20], "big")
            rows = int.from_bytes(d[20:24], "big")
            return (rows, cols) if rows and cols else None
        if n < 4 or d[0] != 0xFF or d[1] != 0xD8:
            return None
        i = 2
        while i + 9 < n:
            if d[i] != 0xFF:
                return None
            marker = d[i + 1]
            if marker == 0xD8 or 0xD0 <= marker <= 0xD9:
                i += 2
                continue
            seg = (d[i + 2] << 8) | d[i + 3]
            if (0xC0 <= marker <= 0xCF and marker not in (0xC4, 0xC8,
                                                          0xCC)):
                rows = (d[i + 5] << 8) | d[i + 6]
                cols = (d[i + 7] << 8) | d[i + 8]
                return (rows, cols) if rows and cols else None
            i += 2 + seg
        return None

    def _decode(self, raw):
        """Header + pixels; JPEG/PNG decode picks the reduced-DCT scale
        (IMREAD_REDUCED_*) exactly like the native engine when the
        resize/crop target permits (byte-level header probe, no extra
        decode)."""
        import cv2

        iscolor = 1 if self.data_shape[0] == 3 else 0
        header, blob = rec.unpack(raw)
        if blob[:4] == rec._RAW_MAGIC or not self.scaled_decode:
            return rec.unpack_img(raw, iscolor)
        probed = self._probe_size(blob)
        if probed is None:
            return rec.unpack_img(raw, iscolor)
        rows, cols = probed
        buf = np.frombuffer(blob, np.uint8)
        c, h, w = self.data_shape
        need = self.resize if self.resize > 0 else max(h, w)
        flags = {8: cv2.IMREAD_REDUCED_COLOR_8,
                 4: cv2.IMREAD_REDUCED_COLOR_4,
                 2: cv2.IMREAD_REDUCED_COLOR_2} if iscolor else \
                {8: cv2.IMREAD_REDUCED_GRAYSCALE_8,
                 4: cv2.IMREAD_REDUCED_GRAYSCALE_4,
                 2: cv2.IMREAD_REDUCED_GRAYSCALE_2}
        for k in (8, 4, 2):
            if rows // k >= max(need, h) and cols // k >= max(need, w):
                img = cv2.imdecode(buf, flags[k])
                if img is not None and img.ndim == 3:
                    img = img[:, :, ::-1]  # BGR -> RGB like unpack_img
                if img is not None:
                    return header, img
                break
        return rec.unpack_img(raw, iscolor)

    def _load(self, offset, rng):
        import cv2
        self.reader.seek(offset)
        raw = self.reader.read()
        header, img = self._decode(raw)
        c, h, w = self.data_shape
        if self.resize > 0:
            shorter = min(img.shape[0], img.shape[1])
            s = self.resize / shorter
            img = cv2.resize(img, None, fx=s, fy=s)
        if img.shape[0] < h or img.shape[1] < w:
            img = cv2.resize(img, (max(img.shape[1], w),
                                   max(img.shape[0], h)))
        if self.rand_crop:
            y0 = rng.randint(0, img.shape[0] - h + 1)
            x0 = rng.randint(0, img.shape[1] - w + 1)
        else:
            y0 = (img.shape[0] - h) // 2
            x0 = (img.shape[1] - w) // 2
        img = img[y0:y0 + h, x0:x0 + w]
        if self.rand_mirror and rng.randint(2):
            img = img[:, ::-1]
        if self.max_rotate_angle:
            # works for 2-D grayscale and 3-D color alike
            angle = rng.uniform(-self.max_rotate_angle,
                                self.max_rotate_angle)
            m = cv2.getRotationMatrix2D((w / 2.0, h / 2.0), angle, 1.0)
            img = cv2.warpAffine(np.ascontiguousarray(img), m, (w, h),
                                 borderMode=cv2.BORDER_REFLECT)
        if (self.random_h or self.random_s or self.random_l) and \
                img.ndim == 3 and img.shape[2] == 3:
            # reference image_augmenter.h HSL jitter: additive uniform
            # noise per channel in HLS space
            hls = cv2.cvtColor(np.ascontiguousarray(img), cv2.COLOR_RGB2HLS)
            hls = hls.astype(np.float32)
            hls[..., 0] += rng.uniform(-self.random_h, self.random_h)
            hls[..., 1] += rng.uniform(-self.random_l, self.random_l)
            hls[..., 2] += rng.uniform(-self.random_s, self.random_s)
            hls[..., 0] %= 180.0
            img = cv2.cvtColor(np.clip(hls, 0, 255).astype(np.uint8),
                               cv2.COLOR_HLS2RGB)
        if img.ndim == 2:
            img = img[:, :, None]
        if self.out_uint8:
            # device-augment mode: raw uint8 HWC RGB; crop already done
            return (np.ascontiguousarray(img, np.uint8),
                    self._header_label(header))
        out = img.astype(np.float32)
        if self.mean_arr is not None:
            out = out - self.mean_arr.transpose(1, 2, 0)
            out = out * self.scale
        else:
            out = (out - self.means[:c]) * self.scale
        return out.transpose(2, 0, 1), self._header_label(header)

    def batch_buffers(self):
        """Freshly allocated (data, label) arrays of one batch's shape —
        also the slot layout of the worker pool's shared-memory rings."""
        c, h, w = self.data_shape
        if self.out_uint8:
            data = np.zeros((self.batch_size, h, w, c), np.uint8)
        else:
            data = np.zeros((self.batch_size, c, h, w), np.float32)
        label = np.zeros((self.batch_size, self.label_width), np.float32)
        return data, label

    def load_batch(self, order, epoch, b, data=None, label=None):
        """Decode epoch ``epoch``'s batch ``b`` of ``order`` into
        (data, label, pad) — into the caller's buffers when given (the
        pool's shm slots). Stateless apart from the record reader, so
        any worker can produce any batch."""
        n = len(order)
        start = b * self.batch_size
        count = min(self.batch_size, n - start)
        if data is None:
            data, label = self.batch_buffers()
        for s in range(self.batch_size):
            pos = start + s
            idx = pos % n  # round-over padding
            data[s], label[s] = self._load(order[idx],
                                           _record_rng(self.seed, epoch,
                                                       pos))
        return data, label, self.batch_size - count

    def next(self):
        n = len(self.order)
        if self.cursor >= n:
            return None
        count = min(self.batch_size, n - self.cursor)
        if not self.round_batch and count < self.batch_size:
            return None
        out = self.load_batch(self.order, self.cur_epoch,
                              self.cursor // self.batch_size)
        self.cursor += self.batch_size
        return out

    def close(self):
        reader = getattr(self, "reader", None)
        if reader is not None:
            reader.close()


def _shared_batch_buffers(template, nslots, shared):
    """``nslots`` (data, label) slot pairs shaped like one batch. With
    ``shared`` they live in anonymous MAP_SHARED mmaps created BEFORE
    the fork, so decode workers collate straight into memory the
    consumer reads — the batch itself never crosses a pipe, only a
    (epoch, batch, slot, pad) tuple does."""
    import mmap

    slots = []
    for _ in range(nslots):
        data, label = template.batch_buffers()
        if shared:
            pair = []
            for a in (data, label):
                buf = mmap.mmap(-1, max(a.nbytes, 1))
                pair.append(np.frombuffer(buf, dtype=a.dtype)
                            .reshape(a.shape))
            slots.append(tuple(pair))
        else:
            slots.append((data, label))
    return slots


def _decode_worker_main(cfg, mean_arr, wid, num_workers, ctl_q, out_q,
                        gen, slots, own_process=True):
    """Decode-worker entry point (forked process, or thread in
    worker_mode='thread'): wait for an epoch command, decode this
    worker's round-robin share of the epoch's batches (batch b goes to
    worker b % num_workers) into the shared slot ring, and announce each
    as a tiny (epoch, batch_idx, slot, pad, decode_seconds) tuple on the
    bounded queue.
    A bumped ``gen`` aborts a stale epoch between batches (reset
    mid-epoch); any exception is reported on the queue — loudly — and
    ends the worker."""
    try:
        if own_process:
            # the pool IS the parallelism; nested cv2 threads would
            # oversubscribe the cores. Forked workers only — in thread
            # mode this global would degrade the PARENT's cv2 too.
            try:
                import cv2
                cv2.setNumThreads(0)
            except Exception:
                pass
        eng = _PyEngine(mean_arr=mean_arr, **cfg)
        while True:
            cmd = ctl_q.get()
            if cmd[0] == "quit":
                return
            epoch = cmd[1]
            order = eng.order_for(epoch)
            produced = 0
            for b in range(wid, eng.num_batches(), num_workers):
                if gen.value != epoch:
                    break  # epoch superseded by a reset
                data, label = slots[produced % len(slots)]
                tic = _time.perf_counter()
                _, _, pad = eng.load_batch(order, epoch, b, data, label)
                # decode seconds ride the existing slot message — the
                # consumer process observes them into io.decode_batch_ms
                out_q.put((epoch, b, produced % len(slots), pad,
                           _time.perf_counter() - tic))
                produced += 1
    except BaseException:
        import traceback
        try:
            out_q.put(("error", traceback.format_exc()))
        except Exception:
            pass


class _ParallelEngine:
    """Multi-worker decode pool behind the ``_PyEngine`` interface.

    The epoch's batch list is dealt round-robin across ``num_workers``
    decode workers (forked processes by default — JPEG decode +
    augment is CPU-bound Python/cv2 work; ``worker_mode='thread'``
    keeps everything in-process for debugging). Each worker runs
    read→decode→augment→collate straight into its shared-memory slot
    ring and announces finished batches on a bounded queue
    (``queue_depth`` per worker); the consumer pops worker ``b % W``
    for batch b, so epoch order is deterministic by construction and
    byte-identical to the serial engine (same per-record RNG, same
    per-epoch shuffle).

    Lifecycle: ``reset()`` bumps the shared epoch generation — workers
    abort a stale epoch at the next batch boundary and pick up the new
    epoch command; in-flight stale batches are discarded by tag.
    A worker death (exception OR hard crash) raises MXNetError at the
    consumer instead of hanging the queue. ``close()`` shuts the pool
    down and reaps every worker process.
    """

    #: batches are views of the slot rings — ImageRecordIter.iter_next
    #: copies before wrapping them in long-lived DataBatch arrays
    reuses_buffers = True

    def __init__(self, path, data_shape, batch_size, label_width, means,
                 scale, resize, rand_crop, rand_mirror, shuffle, seed,
                 num_parts, part_index, round_batch, mean_img=None,
                 max_rotate_angle=0, random_h=0, random_s=0, random_l=0,
                 out_uint8=False, scaled_decode=True, path_imgidx=None,
                 num_workers=1, worker_mode="process", queue_depth=None):
        if queue_depth is None:
            queue_depth = int(os.environ.get("MXNET_IO_QUEUE_DEPTH",
                                             "4") or 4)
        self.num_workers = int(num_workers)
        self.queue_depth = max(1, int(queue_depth))
        if worker_mode not in ("process", "thread"):
            raise MXNetError("worker_mode must be 'process' or 'thread', "
                             "got %r" % (worker_mode,))
        # the template engine scans offsets (via the .idx sidecar when
        # given), validates the config, and computes/loads the mean
        # image ONCE in the parent — workers inherit the result
        self._template = _PyEngine(
            path, data_shape, batch_size, label_width, means, scale,
            resize, rand_crop, rand_mirror, shuffle, seed, num_parts,
            part_index, round_batch, mean_img=mean_img,
            max_rotate_angle=max_rotate_angle, random_h=random_h,
            random_s=random_s, random_l=random_l, out_uint8=out_uint8,
            scaled_decode=scaled_decode, path_imgidx=path_imgidx)
        self._template.close()  # the parent never decodes
        self.batch_size = batch_size
        self._nb = self._template.num_batches()
        self._timeout = float(os.environ.get("MXNET_IO_WORKER_TIMEOUT",
                                             "300") or 300)

        use_proc = worker_mode == "process"
        if use_proc:
            import multiprocessing as mp
            try:
                ctx = mp.get_context("fork")
            except ValueError:  # no fork on this platform
                ctx = None
                use_proc = False
        self._is_proc = use_proc

        # worker config: pre-sharded offsets, parent's mean, no
        # mean_img (the parent already resolved it)
        cfg = dict(path=path, data_shape=data_shape,
                   batch_size=batch_size, label_width=label_width,
                   means=tuple(np.asarray(means, np.float32)),
                   scale=scale, resize=resize, rand_crop=rand_crop,
                   rand_mirror=rand_mirror, shuffle=shuffle, seed=seed,
                   num_parts=1, part_index=0, round_batch=round_batch,
                   max_rotate_angle=max_rotate_angle, random_h=random_h,
                   random_s=random_s, random_l=random_l,
                   out_uint8=out_uint8, scaled_decode=scaled_decode,
                   offsets=self._template.offsets)

        nslots = self.queue_depth + 2  # queue_depth announced + 1 the
        # consumer is viewing + 1 being written never collide
        self._slots, self._ctl, self._out, self._workers = [], [], [], []
        if use_proc:
            self._gen = ctx.Value("l", 0)
        else:
            class _Gen:
                value = 0
            self._gen = _Gen()
        for wid in range(self.num_workers):
            slots = _shared_batch_buffers(self._template, nslots,
                                          shared=use_proc)
            if use_proc:
                ctl, out = ctx.Queue(), ctx.Queue(maxsize=self.queue_depth)
                make = ctx.Process
            else:
                ctl, out = _queue.Queue(), \
                    _queue.Queue(maxsize=self.queue_depth)
                make = threading.Thread
            w = make(target=_decode_worker_main,
                     args=(cfg, self._template.mean_arr, wid,
                           self.num_workers, ctl, out, self._gen, slots,
                           use_proc),
                     daemon=True, name="mx-decode-%d" % wid)
            self._slots.append(slots)
            self._ctl.append(ctl)
            self._out.append(out)
            self._workers.append(w)
            import warnings
            with warnings.catch_warnings():
                # jax warns that os.fork() from its (multithreaded)
                # process can deadlock; the decode workers never touch
                # jax — they fork straight into cv2/numpy work, the
                # standard DataLoader-style arrangement
                warnings.filterwarnings(
                    "ignore", message=r".*os\.fork\(\).*",
                    category=RuntimeWarning)
                w.start()
        self._closed = False
        self.epoch = 0
        self.reset()

    # -- _PyEngine interface ------------------------------------------
    @property
    def offsets(self):
        return self._template.offsets

    @property
    def mean_arr(self):
        return self._template.mean_arr

    def reset(self):
        """Start the next epoch: bump the generation (workers abort any
        stale epoch at their next batch boundary) and enqueue the epoch
        command. Stale in-flight batches are discarded by tag in
        ``next`` — never served."""
        if self._closed:
            raise MXNetError("ImageRecordIter worker pool is closed")
        self.cur_epoch = self.epoch
        self.epoch += 1
        self._gen.value = self.cur_epoch
        for ctl in self._ctl:
            ctl.put(("epoch", self.cur_epoch))
        self._next_b = 0

    def _pop(self, wid):
        """Next announcement from worker ``wid``'s queue, discarding
        stale-epoch leftovers; raises on worker failure, death, or
        timeout instead of hanging."""
        deadline = _time.time() + self._timeout
        tic = _time.perf_counter()
        while True:
            try:
                item = self._out[wid].get(timeout=0.2)
            except _queue.Empty:
                if not self._workers[wid].is_alive():
                    self.close()
                    raise MXNetError(
                        "decode worker %d died (killed or crashed "
                        "without a traceback) — batch %d will never "
                        "arrive" % (wid, self._next_b))
                if _time.time() > deadline:
                    self.close()
                    raise MXNetError(
                        "decode worker %d produced nothing for %.0f s "
                        "(MXNET_IO_WORKER_TIMEOUT)"
                        % (wid, self._timeout))
                continue
            if item[0] == "error":
                self.close()
                raise MXNetError("decode worker %d failed:\n%s"
                                 % (wid, item[1]))
            if item[0] != self.cur_epoch:
                continue  # leftover from before a reset
            wait = _time.perf_counter() - tic
            _TM_POOL_WAIT_MS.observe(wait * 1e3)
            if wait > 1e-3:  # the pool starved the consumer
                _TM_POOL_STARVED.inc()
            return item

    def next(self):
        if self._next_b >= self._nb:
            return None
        b = self._next_b
        wid = b % self.num_workers
        epoch, got_b, slot, pad, decode_s = self._pop(wid)
        if got_b != b:  # pragma: no cover — protocol invariant
            self.close()
            raise MXNetError(
                "decode pool out of order: expected batch %d from "
                "worker %d, got %d" % (b, wid, got_b))
        _TM_DECODE_MS.observe(decode_s * 1e3)
        _TM_POOL_BATCHES.inc()
        try:
            # ready batches still queued across the WHOLE pool (a
            # healthy pool keeps this near num_workers * queue_depth;
            # a worker-local qsize would under-report W-fold and hide
            # a single straggler behind its siblings)
            _TM_POOL_QDEPTH.set(sum(q.qsize() for q in self._out))
        except NotImplementedError:  # qsize absent on some platforms
            pass
        self._next_b += 1
        data, label = self._slots[wid][slot]
        return data, label, pad

    def close(self):
        """Shut the pool down: abort in-flight epochs, drain queues so
        blocked workers can exit, and reap every process."""
        if getattr(self, "_closed", True):
            return
        self._closed = True
        self._gen.value = -1
        for ctl in self._ctl:
            try:
                ctl.put(("quit",))
            except Exception:
                pass
        deadline = _time.time() + 5.0
        for wid, w in enumerate(self._workers):
            while w.is_alive() and _time.time() < deadline:
                try:  # unblock a worker stuck in a full-queue put
                    self._out[wid].get_nowait()
                except _queue.Empty:
                    pass
                w.join(timeout=0.05)
            if self._is_proc and w.is_alive():
                w.terminate()
                w.join(timeout=1.0)
        if self._is_proc:
            for q in self._ctl + self._out:
                q.cancel_join_thread()
                q.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class DeviceAugmentIter(DataIter):
    """Wrap a ``device_augment=True`` ImageRecordIter: uint8 HWC batches
    cross to the device (4x less infeed traffic) and random
    crop/flip/normalize run THERE in one small jitted program; yields
    normalized float NCHW batches like the host pipeline would.

    The production recipe (doc/performance.md "Input pipeline"): host =
    decode + resize + center-crop to the storage shape; device = the
    random augmentations. ``crop_shape=(h, w)`` is the training crop
    (default: the storage shape, i.e. no crop).

    For the tightest loop, fuse ``device_augment_batch`` directly into
    your compiled train step instead; this wrapper keeps the plain
    DataIter protocol so FeedForward/Trainer code runs unchanged.
    """

    def __init__(self, base, crop_shape=None, rand_crop=True,
                 rand_mirror=True, mean=(0.0, 0.0, 0.0), scale=1.0,
                 seed=0):
        import jax

        super().__init__()
        if not getattr(base, "_device_augment", False):
            raise MXNetError("DeviceAugmentIter needs an ImageRecordIter "
                             "created with device_augment=True")
        self._base = base
        self.batch_size = base.batch_size
        c, big_h, big_w = base._data_shape
        self._crop = tuple(crop_shape) if crop_shape else (big_h, big_w)
        if self._crop[0] > big_h or self._crop[1] > big_w:
            raise MXNetError(
                "DeviceAugmentIter: crop_shape %s exceeds the base "
                "iterator's storage shape (%d, %d)"
                % (self._crop, big_h, big_w))
        self._chans = c
        self._key = jax.random.PRNGKey(seed)
        self._step = 0
        self._data = None
        self._label = None
        self._pad = 0

        rc, rm = bool(rand_crop), bool(rand_mirror)
        mean_t, scale_f = tuple(float(m) for m in mean), float(scale)
        crop = self._crop

        def _augment(u8, key):
            return device_augment_batch(
                u8, key=key, crop_shape=crop, rand_crop=rc,
                rand_mirror=rm, mean=mean_t, scale=scale_f)

        self._augment = jax.jit(_augment)

    @property
    def provide_data(self):
        h, w = self._crop
        return [(self._base._data_name,
                 (self.batch_size, self._chans, h, w))]

    @property
    def provide_label(self):
        return self._base.provide_label

    def reset(self):
        self._base.reset()

    def iter_next(self):
        import jax

        if not self._base.iter_next():
            return False
        self._step += 1
        key = jax.random.fold_in(self._key, self._step)
        u8 = self._base._data._val  # [B, H, W, C] uint8 on device
        self._data = nd.NDArray._from_jax(self._augment(u8, key),
                                          self._base._data.context)
        self._label = self._base._label
        self._pad = self._base.getpad()
        return True

    def getdata(self):
        return [self._data]

    def getlabel(self):
        return [self._label]

    def getpad(self):
        return self._pad
