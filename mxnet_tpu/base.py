"""Base types, dtype codes and error handling for the TPU-native rebuild.

The reference exposes a ctypes C ABI (``/root/reference/python/mxnet/base.py``,
``include/mxnet/c_api.h``). Here the runtime is in-process (JAX/XLA), so this
module keeps only the pieces with user-visible semantics: the mshadow dtype
codes used by the checkpoint format (``include/mxnet/base.h``, mshadow
``kFloat32..kInt32``) and the ``MXNetError`` exception type.
"""
from __future__ import annotations

import numpy as np

__all__ = ["MXNetError", "mx_uint", "mx_float", "string_types",
           "DTYPE_NP_TO_MX", "DTYPE_MX_TO_NP"]


class MXNetError(Exception):
    """Error raised by the framework (parity: ``MXGetLastError`` errors)."""


string_types = (str,)
mx_uint = int
mx_float = float

# mshadow type codes — used on disk by the NDArray save format and by the
# C-API dtype handshake (reference: mshadow/base.h kFloat32=0, kFloat64=1,
# kFloat16=2, kUint8=3, kInt32=4).
DTYPE_NP_TO_MX = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.float16): 2,
    np.dtype(np.uint8): 3,
    np.dtype(np.int32): 4,
}
DTYPE_MX_TO_NP = {v: k for k, v in DTYPE_NP_TO_MX.items()}

# TPU-era extension codes (not in the 2015 reference): bfloat16 is the native
# MXU dtype. Code chosen outside the reference range so reference files never
# collide.
try:  # ml_dtypes ships with jax
    import ml_dtypes  # noqa: F401

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
    DTYPE_NP_TO_MX[_BFLOAT16] = 16
    DTYPE_MX_TO_NP[16] = _BFLOAT16
except ImportError:  # pragma: no cover
    _BFLOAT16 = None


def np_dtype(dtype) -> np.dtype:
    """Normalize a user-provided dtype to a numpy dtype we support."""
    dt = np.dtype(dtype)
    if dt not in DTYPE_NP_TO_MX:
        raise MXNetError("unsupported dtype %s" % dt)
    return dt


def check_call(ret):
    """Kept for API parity with the ctypes binding; a no-op in-process."""
    if ret != 0:
        raise MXNetError("API call returned %s" % ret)
