"""Base types, dtype codes and error handling for the TPU-native rebuild.

The reference exposes a ctypes C ABI (``/root/reference/python/mxnet/base.py``,
``include/mxnet/c_api.h``). Here the runtime is in-process (JAX/XLA), so this
module keeps only the pieces with user-visible semantics: the mshadow dtype
codes used by the checkpoint format (``include/mxnet/base.h``, mshadow
``kFloat32..kInt32``) and the ``MXNetError`` exception type.
"""
from __future__ import annotations

import numpy as np

__all__ = ["c_str", "c_array", "ctypes2buffer", "ctypes2numpy_shared", "ctypes2docstring", "MXNetError", "mx_uint", "mx_float", "string_types",
           "DTYPE_NP_TO_MX", "DTYPE_MX_TO_NP"]


class MXNetError(Exception):
    """Error raised by the framework (parity: ``MXGetLastError`` errors)."""


string_types = (str,)
mx_uint = int
mx_float = float

# mshadow type codes — used on disk by the NDArray save format and by the
# C-API dtype handshake (reference: mshadow/base.h kFloat32=0, kFloat64=1,
# kFloat16=2, kUint8=3, kInt32=4).
DTYPE_NP_TO_MX = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.float16): 2,
    np.dtype(np.uint8): 3,
    np.dtype(np.int32): 4,
}
DTYPE_MX_TO_NP = {v: k for k, v in DTYPE_NP_TO_MX.items()}

# TPU-era extension codes (not in the 2015 reference): bfloat16 is the native
# MXU dtype. Code chosen outside the reference range so reference files never
# collide.
try:  # ml_dtypes ships with jax
    import ml_dtypes  # noqa: F401

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
    DTYPE_NP_TO_MX[_BFLOAT16] = 16
    DTYPE_MX_TO_NP[16] = _BFLOAT16
except ImportError:  # pragma: no cover
    _BFLOAT16 = None


def np_dtype(dtype) -> np.dtype:
    """Normalize a user-provided dtype to a numpy dtype we support."""
    dt = np.dtype(dtype)
    if dt not in DTYPE_NP_TO_MX:
        raise MXNetError("unsupported dtype %s" % dt)
    return dt


def check_call(ret):
    """Kept for API parity with the ctypes binding; a no-op in-process."""
    if ret != 0:
        raise MXNetError("API call returned %s" % ret)


# ---------------------------------------------------------------------------
# ctypes helpers (reference base.py:79-186) — used by binding authors
# talking to the native C ABI (cpp/c_api_graph.h) from Python.

def c_str(string):
    """Create a ctypes char* from a python string."""
    import ctypes
    return ctypes.c_char_p(string.encode("utf-8"))


def c_array(ctype, values):
    """Create a ctypes array from a python list."""
    return (ctype * len(values))(*values)


def ctypes2buffer(cptr, length):
    """Convert a ctypes pointer to a bytearray of `length` bytes."""
    import ctypes
    if not isinstance(cptr, ctypes.POINTER(ctypes.c_char)):
        raise TypeError("expected char pointer")
    res = bytearray(length)
    rptr = (ctypes.c_char * length).from_buffer(res)
    if not ctypes.memmove(rptr, cptr, length):
        raise RuntimeError("memmove failed")
    return res


def ctypes2numpy_shared(cptr, shape):
    """View a ctypes float pointer as a numpy array sharing memory."""
    import ctypes
    if not isinstance(cptr, ctypes.POINTER(ctypes.c_float)):
        raise TypeError("expected float pointer")
    size = 1
    for s in shape:
        size *= s
    dbuffer = (ctypes.c_float * size).from_address(
        ctypes.addressof(cptr.contents))
    return np.frombuffer(dbuffer, dtype=np.float32).reshape(shape)


def ctypes2docstring(num_args, arg_names, arg_types, arg_descs,
                     remove_dup=True):
    """Convert C-registry argument metadata to a parameter docstring."""
    param_keys = set()
    param_str = []
    for i in range(num_args.value if hasattr(num_args, "value")
                   else num_args):
        key = arg_names[i]
        if isinstance(key, bytes):
            key = key.decode("utf-8")
        if key in param_keys and remove_dup:
            continue
        param_keys.add(key)
        t = arg_types[i]
        if isinstance(t, bytes):
            t = t.decode("utf-8")
        d = arg_descs[i]
        if isinstance(d, bytes):
            d = d.decode("utf-8")
        ret = "%s : %s" % (key, t)
        if d:
            ret += "\n    " + d
        param_str.append(ret)
    return "Parameters\n----------\n%s\n" % ("\n".join(param_str))
