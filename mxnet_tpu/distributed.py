"""Multi-host runtime initialization.

TPU-native replacement for the reference's process bootstrap: ps-lite's
scheduler/server/worker roles wired through ``DMLC_ROLE``/``DMLC_PS_ROOT_*``
env vars (src/kvstore/kvstore_dist.h, python/mxnet/kvstore_server.py,
tools/launch.py trackers). There are no server processes here — every
process is a worker holding a slice of one global device mesh, and
cross-host traffic is XLA collectives over ICI/DCN. What remains of the
bootstrap is JAX distributed initialization: coordinator address + process
count + process id, carried in ``MXNET_TPU_*`` env vars (set by
``tools/launch.py``) or auto-detected on real TPU pods.
"""
from __future__ import annotations

import os

import jax

__all__ = ["initialize", "is_initialized", "rank", "num_workers",
           "local_devices", "barrier", "shutdown"]

_initialized = False


def initialize(coordinator=None, num_processes=None, process_id=None,
               local_device_count=None):
    """Initialize the multi-process runtime.

    With no args: reads ``MXNET_TPU_COORDINATOR`` / ``MXNET_TPU_NUM_WORKERS``
    / ``MXNET_TPU_RANK`` (set by tools/launch.py), else tries TPU-pod
    auto-detection, else becomes a single-process run (no-op).

    ``local_device_count`` forces N virtual CPU devices per process
    (testing multi-host on localhost, SURVEY.md §4's "real processes on one
    machine" strategy).
    """
    global _initialized
    if _initialized:
        return
    coordinator = coordinator or os.environ.get("MXNET_TPU_COORDINATOR")
    if num_processes is None and "MXNET_TPU_NUM_WORKERS" in os.environ:
        num_processes = int(os.environ["MXNET_TPU_NUM_WORKERS"])
    if process_id is None and "MXNET_TPU_RANK" in os.environ:
        process_id = int(os.environ["MXNET_TPU_RANK"])
    if local_device_count is None and "MXNET_TPU_LOCAL_DEVICES" in os.environ:
        local_device_count = int(os.environ["MXNET_TPU_LOCAL_DEVICES"])

    if local_device_count is not None:
        # must run before backend init
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices",
                              int(local_device_count))
        except AttributeError:
            # older jax has no jax_num_cpu_devices option; the CPU
            # device count is an XLA flag there, read lazily at first
            # backend init — which has not happened yet on this path.
            # An explicit local_device_count wins over a pre-existing
            # flag value (a stale debugging leftover would otherwise
            # silently size the mesh wrong), loudly.
            import logging
            import re
            flags = os.environ.get("XLA_FLAGS", "")
            want = ("--xla_force_host_platform_device_count=%d"
                    % int(local_device_count))
            if "xla_force_host_platform_device_count" in flags:
                updated = re.sub(
                    r"--xla_force_host_platform_device_count=\d+",
                    want, flags)
                if updated != flags:
                    logging.warning(
                        "distributed.initialize: replacing "
                        "xla_force_host_platform_device_count in "
                        "XLA_FLAGS with the explicitly requested %d",
                        int(local_device_count))
                os.environ["XLA_FLAGS"] = updated
            else:
                os.environ["XLA_FLAGS"] = (flags + " " + want).strip()
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
    # generous join deadline: on oversubscribed hosts (the 1-core CI
    # box runs 4 jax processes) a peer's XLA compile can stall it for
    # minutes before it reaches the rendezvous; the default 5-minute
    # window was the main source of coordination-service flakes
    init_timeout = int(os.environ.get("MXNET_TPU_INIT_TIMEOUT", 600))
    if coordinator is None and num_processes is None:
        # single process (or TPU pod with full auto-detection)
        try:
            jax.distributed.initialize(
                initialization_timeout=init_timeout)
        except Exception:
            pass  # not in a managed multi-host environment
    else:
        jax.distributed.initialize(coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id,
                                   initialization_timeout=init_timeout)
    _initialized = True


def is_initialized():
    return _initialized


def rank():
    """This process's index (reference: kvstore rank / DMLC worker id)."""
    return jax.process_index()


def num_workers():
    return jax.process_count()


def local_devices():
    return jax.local_devices()


def barrier(name="mxnet_tpu_barrier"):
    """Global process barrier (reference Postoffice::Barrier)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


def shutdown():
    global _initialized
    if _initialized and jax.process_count() > 1:
        jax.distributed.shutdown()
    _initialized = False
