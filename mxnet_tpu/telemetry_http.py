"""HTTP exposition of the telemetry plane (doc/observability.md).

Until now every consumer of ``mx.telemetry`` lived INSIDE the process:
``snapshot()`` and ``to_prometheus()`` are Python calls. This module
puts them on the wire — a stdlib ``http.server`` daemon thread serving
strictly read-only GET endpoints:

``/metrics``
    Prometheus text exposition (``to_prometheus()``), refreshed with
    the best-effort program/device introspection gauges and the
    serving SLO burn rates before rendering — what a Prometheus
    scraper or the ROADMAP item 1 admission router polls.
    ``?prefix=serving.`` restricts to one dotted-name subtree (a
    fleet scraper pulling only the serving metrics).
``/snapshot``
    ``snapshot()`` as JSON (non-finite floats serialized as null);
    honors the same ``?prefix=`` filter.
``/rounds``
    Recent round-phase ledgers across every engine: each serving
    round's wall time decomposed into drain / prefix lookup / h2d /
    prefill / copy / dispatch / host-scheduling phases
    (``?n=<rows>``, default 64 per engine) — where a p99 round's
    time actually went.
``/requests``
    Live + recently-retired serving request table across every engine
    in the process.
``/flight/<request_id>``
    One request's flight-recorder timeline (submit → … → retire
    reason), available after retirement for the last
    ``MXNET_SERVING_FLIGHT_RECORDER`` retired requests.
``/fleet``
    Aggregated fleet plane across every live :class:`FleetRouter`:
    per-replica role/health/occupancy, handoff stats, SLO thresholds
    + burn readings, and the stitched-journey ring occupancy.
``/fleet/flight/<trace_id>``
    One request's STITCHED cross-replica journey (router + wire +
    per-engine events on one monotonic clock, SLO decomposition in
    the meta); ``?chrome=1`` returns the Perfetto/chrome-trace export
    (one track per replica).
``/healthz``
    Engine liveness fed by the PR 7 watchdog state: 200 while no
    engine is stuck, 503 when a ``round_timeout_ms`` trip has not yet
    drained (a router should stop sending traffic here).

Everything is host-side — handlers read host bookkeeping and host-
cached analyses; nothing dispatches a device op or forces a sync. The
server is opt-in: ``mx.telemetry.serve(port=0)`` (ephemeral port, the
handle carries ``.url``) or ``MXNET_TELEMETRY_PORT=<port>`` at import.
It binds ``127.0.0.1`` by default — pass ``host="0.0.0.0"`` explicitly
to scrape across machines. One server per process; re-``serve`` stops
the previous one, and an armed server stops cleanly at interpreter
exit.
"""
from __future__ import annotations

import atexit
import json
import http.server
import logging
import math
import os
import sys
import threading

from . import telemetry

__all__ = ["serve", "stop_server", "TelemetryServer"]

_log = logging.getLogger(__name__)
_server = None
_server_lock = threading.Lock()


def _engines():
    """Live InferenceEngines in this process (empty when serving was
    never imported — the plane works for training-only processes)."""
    eng = sys.modules.get("mxnet_tpu.serving.engine")
    if eng is None:
        return []
    try:
        return list(eng._ENGINES)
    except Exception:
        return []


def _routers():
    """Live FleetRouters in this process (weak registry in
    serving.fleet; empty when the fleet layer was never imported)."""
    fleet = sys.modules.get("mxnet_tpu.serving.fleet")
    if fleet is None:
        return []
    try:
        return [r for r in fleet._ROUTERS if not r._closed]
    except Exception:
        return []


def _refresh():
    """Pre-scrape refresh, all best-effort and host-side: program
    cost analyses (cached lowerings — no compile, no trace), device
    memory gauges, serving + fleet SLO burn rates. A failure in any
    refresher must never fail the scrape."""
    try:
        from . import profiler
        profiler.collect_program_stats()
        profiler.device_memory()
    except Exception:
        pass
    for e in _engines():
        try:
            e._slo_tick()
        except Exception:
            pass
    for r in _routers():
        try:
            r._slo_tick()
        except Exception:
            pass


def _scrub(obj):
    """JSON-safe copy: non-finite floats become null (strict JSON has
    no NaN/Infinity, and /snapshot promises round-trippable output)."""
    if isinstance(obj, dict):
        return {k: _scrub(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_scrub(v) for v in obj]
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    return obj


def _route(path, query=None):
    """Dispatch one GET: returns (status, content_type, body bytes).
    ``query`` is the parsed query string (first value per key):
    ``/metrics`` and ``/snapshot`` honor ``?prefix=<dotted-prefix>``
    (a fleet scraper pulling only the ``serving.`` subtree),
    ``/rounds`` honors ``?n=<rows>``."""
    query = query or {}
    prefix = query.get("prefix") or None
    if path in ("/metrics", "/metrics/"):
        _refresh()
        return (200, "text/plain; version=0.0.4; charset=utf-8",
                telemetry.to_prometheus(prefix=prefix).encode())
    if path in ("/snapshot", "/snapshot/"):
        _refresh()
        body = json.dumps(_scrub(telemetry.snapshot(prefix=prefix)),
                          sort_keys=True)
        return 200, "application/json", body.encode()
    if path in ("/rounds", "/rounds/"):
        # recent round-phase ledgers (read-only — the engine appends,
        # this copies): one block per engine, newest rounds last
        try:
            n = max(1, int(query.get("n", 64)))
        except (TypeError, ValueError):
            n = 64
        engines = []
        for i, e in enumerate(_engines()):
            try:
                engines.append({"engine": i,
                                "rounds": e.round_table(n)})
            except Exception:
                continue
        return (200, "application/json",
                json.dumps({"engines": _scrub(engines)}).encode())
    if path in ("/requests", "/requests/"):
        rows = []
        for e in _engines():
            try:
                rows.extend(e.request_table())
            except Exception:
                continue
        return (200, "application/json",
                json.dumps({"requests": _scrub(rows)}).encode())
    if path.startswith("/fleet/flight/"):
        rid = path[len("/fleet/flight/"):].rstrip("/")
        chrome = query.get("chrome") in ("1", "true", "yes")
        for r in _routers():
            try:
                tl = r.flight.chrome_trace(rid) if chrome \
                    else r.flight.timeline(rid)
            except Exception:
                tl = None
            if tl is not None:
                return (200, "application/json",
                        json.dumps(_scrub(tl)).encode())
        return (404, "application/json",
                json.dumps({"error": "no stitched journey for trace "
                            "%r (ring keeps the last N retired "
                            "journeys per router)" % rid}).encode())
    if path in ("/fleet", "/fleet/"):
        _refresh()
        fleets = []
        for r in _routers():
            try:
                fleets.append(r.fleet_table())
            except Exception:
                continue
        return (200, "application/json",
                json.dumps({"fleets": _scrub(fleets)}).encode())
    if path.startswith("/flight/"):
        rid = path[len("/flight/"):].rstrip("/")
        keys = [rid]
        if rid.lstrip("-").isdigit():
            keys.insert(0, int(rid))   # auto-assigned integer ids
        for e in _engines():
            for k in keys:
                try:
                    tl = e.flight.timeline(k)
                except Exception:
                    tl = None
                if tl is not None:
                    return (200, "application/json",
                            json.dumps(_scrub(tl)).encode())
        return (404, "application/json",
                json.dumps({"error": "no flight record for request "
                            "%r (ring keeps the last N retired "
                            "requests)" % rid}).encode())
    if path in ("/healthz", "/healthz/"):
        engines = []
        for e in _engines():
            try:
                engines.append(e.health())
            except Exception:
                continue
        # a closed engine can never recover and must not wedge the
        # health signal — only a LIVE engine's tripped watchdog is
        # actionable (stop routing here)
        stuck = any(h.get("stuck") and not h.get("closed")
                    for h in engines)
        doc = {"status": "stuck" if stuck else "ok",
               "engines": engines}
        return (503 if stuck else 200, "application/json",
                json.dumps(_scrub(doc)).encode())
    if path in ("/", ""):
        return (200, "application/json", json.dumps(
            {"endpoints": ["/metrics", "/snapshot", "/requests",
                           "/flight/<request_id>", "/rounds",
                           "/fleet", "/fleet/flight/<trace_id>",
                           "/healthz"]}
        ).encode())
    return (404, "application/json",
            json.dumps({"error": "unknown path %r" % path}).encode())


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = "mxnet-telemetry/1"
    protocol_version = "HTTP/1.1"

    def do_GET(self):             # noqa: N802 — http.server contract
        try:
            from urllib.parse import parse_qsl
            path, _, qs = self.path.partition("?")
            query = dict(parse_qsl(qs))
            status, ctype, body = _route(path, query)
        except Exception as e:    # noqa: BLE001 — a scrape never kills
            _log.warning("telemetry http: %s handling %r", e, self.path)
            status, ctype = 500, "application/json"
            body = json.dumps({"error": str(e)}).encode()
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):            # noqa: N802 — strictly read-only
        body = json.dumps({"error": "read-only endpoint"}).encode()
        self.send_response(405)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.send_header("Allow", "GET")
        self.end_headers()
        self.wfile.write(body)

    do_PUT = do_DELETE = do_PATCH = do_POST

    def log_message(self, fmt, *args):
        _log.debug("telemetry http: " + fmt, *args)


class TelemetryServer:
    """Handle for a running exposition server (``serve()`` returns
    one): ``.host`` / ``.port`` / ``.url`` and ``.stop()``."""

    def __init__(self, port=0, host="127.0.0.1"):
        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      _Handler)
        self._httpd.daemon_threads = True
        self.host = self._httpd.server_address[0]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            daemon=True, name="mx-telemetry-http")
        self._thread.start()

    @property
    def url(self):
        host = "127.0.0.1" if self.host in ("0.0.0.0", "::", "") \
            else self.host
        return "http://%s:%d" % (host, self.port)

    @property
    def running(self):
        return self._thread.is_alive()

    def stop(self):
        """Shut the listener down and release the port (idempotent;
        registered atexit for the process-level server)."""
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5)

    def __repr__(self):
        return "TelemetryServer(url=%r, running=%s)" % (self.url,
                                                        self.running)


def serve(port=0, host="127.0.0.1"):
    """Start the process's exposition server (see the module
    docstring). Restarting replaces the previous server. Returns the
    :class:`TelemetryServer` handle."""
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
        _server = TelemetryServer(port=port, host=host)
        return _server


def stop_server():
    """Stop the process's exposition server (no-op when none runs)."""
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None


atexit.register(stop_server)

# import-time arm: MXNET_TELEMETRY_PORT=<port> starts the server with
# the process (0 = ephemeral — the chosen port is logged). A bad knob
# must not take down `import mxnet_tpu`.
_port = os.environ.get("MXNET_TELEMETRY_PORT")
if _port:
    try:
        _srv = serve(port=int(_port))
        _log.info("telemetry: exposition server listening on %s",
                  _srv.url)
    except Exception as _e:
        logging.warning("MXNET_TELEMETRY_PORT=%r is unusable (%s) — "
                        "exposition server not started", _port, _e)
